"""Pass (e): lock-order analysis — deadlock freedom by construction.

The races pass proves shared state is *locked*; this pass proves the
locks themselves are taken in one global *order*.  Two threads that
acquire the same two locks in opposite orders deadlock the moment their
critical sections overlap — the classic inversion no amount of
per-attribute locking prevents, and the failure mode every new
cross-thread subsystem (replicated ds log, sharded prep) risks adding.

Model:

* a **lock identity** is class-qualified (``ChurnWal._lock``) for
  ``self.<attr> = threading.Lock()/RLock()/Condition()`` attributes —
  one name per (class, attr), the standard instance-collapsed
  approximation — or module-qualified (``emqx_tpu.ops.native._lock``)
  for module-level locks.  ``asyncio.Lock()`` family locks are tracked
  too (kind ``async``): ordering cycles between coroutines deadlock the
  loop just as surely, they just park tasks instead of threads.
* per function, a statement-ordered scan tracks the **held set**
  through ``with``/``async with`` blocks AND bare ``.acquire()`` /
  ``.release()`` calls (an acquire with no matching release makes the
  lock part of the function's *holds-on-exit* summary; a release with
  no prior acquire, its *releases-on-entry* summary — the
  begin()/end() split-guard idiom).
* acquiring M while holding L adds the edge **L -> M**.  Calls resolve
  through the PR 8 call graph: an edge is added for every lock the
  callee may acquire transitively (``CALL`` edges and ``EXECUTOR``
  hops both count — ``await asyncio.to_thread(f)`` while holding L
  still nests every lock f takes under L in wait-for terms).
* any cycle in the merged graph is an **error** (``lock-cycle``).
  Same-name self-edges are excluded: for RLocks re-entry is legal, and
  for distinct instances of one class the name collapse would make
  every peer-to-peer call a false cycle.  The one provably-deadlocking
  shape — re-acquiring a NON-reentrant lock on the same ``self``
  receiver, directly or through a ``self.method()`` hop chain — is
  reported separately (``lock-reentry``).
* ``tools/analysis/lockorder.json`` records the blessed global order.
  An edge between two listed locks that runs *backwards* is an
  inversion error (``lock-order``) unless the acquisition line carries
  ``# analysis: lock-after=<held>`` naming the held lock — the escape
  documents a reviewed exception in place.  Listed names that match no
  known lock are flagged (``lockorder-dead``) so the file can't rot.
* an ``await`` while a *threading* lock is held **non-lexically**
  (via ``.acquire()`` or a call into a holds-on-exit function) is an
  error (``await-under-lock-hop``) — the lexical ``with self._lock:
  ... await`` case is already covered by the races pass; this closes
  the split-guard hole the lexical check cannot see.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .index import CALL, EXECUTOR, FuncInfo, ProjectIndex, _attr_chain, \
    _walk_own_body
from .report import ERROR, WARN, Finding

LOCKORDER_NAME = "lockorder.json"

_THREAD_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
                 "BoundedSemaphore"}
_ASYNC_HEAD = "asyncio"


def lockorder_path(repo: str) -> str:
    return os.path.join(repo, "tools", "analysis", LOCKORDER_NAME)


@dataclass
class LockDef:
    name: str  # "ChurnWal._lock" | "emqx_tpu.ops.native._lock"
    kind: str  # "thread" | "async"
    reentrant: bool
    path: str
    line: int


@dataclass
class LockEdge:
    held: str
    acquired: str
    path: str
    line: int
    func: str  # qualname of the function holding `held`
    roles: str = "?"  # thread roles of that function ("loop/worker")
    blessed: bool = False  # carries a matching lock-after annotation


@dataclass
class _Held:
    name: str
    kind: str
    via: str  # "with" | "acquire" | "call"
    chain: str  # source receiver text ("self._lock"), "" via call


@dataclass
class _FnScan:
    """Per-function facts from one statement-ordered walk."""
    events: List[tuple] = field(default_factory=list)
    # direct lock names acquired anywhere (with or acquire)
    acquires: Set[str] = field(default_factory=set)
    # locks acquired on a literal `self` receiver (for reentry checks)
    self_acquires: Set[str] = field(default_factory=set)
    holds_on_exit: Set[str] = field(default_factory=set)
    releases_on_entry: Set[str] = field(default_factory=set)


class LockAnalysis:
    def __init__(self, idx: ProjectIndex, roles: Dict[str, Set[str]],
                 package_prefix: str = "emqx_tpu"):
        self.idx = idx
        self.roles = roles
        self.prefix = package_prefix
        self.locks: Dict[str, LockDef] = {}
        # class name -> {attr -> lock name}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        # module -> {global name -> lock name}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        self.edges: List[LockEdge] = []
        self.findings: List[Finding] = []
        self.scans: Dict[str, _FnScan] = {}
        self.summary: Dict[str, Set[str]] = {}
        self.summary_self: Dict[str, Set[str]] = {}

    # ------------------------------------------------------- lock registry

    def collect_locks(self) -> None:
        for cls_list in self.idx.classes.values():
            for ci in cls_list:
                if not ci.module.startswith(self.prefix):
                    continue
                for m in ci.methods.values():
                    for node in ast.walk(m.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        got = self._lock_ctor(node.value)
                        if got is None:
                            continue
                        kind, reentrant = got
                        for t in node.targets:
                            tc = _attr_chain(t)
                            if tc and tc[0] == "self" and len(tc) == 2:
                                name = f"{ci.name}.{tc[1]}"
                                self.locks[name] = LockDef(
                                    name, kind, reentrant, ci.path,
                                    node.lineno)
                                self.class_locks.setdefault(
                                    ci.name, {})[tc[1]] = name
        for module, fi in self.idx.modules.items():
            if fi.tree is None or not module.startswith(self.prefix):
                continue
            for node in fi.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                got = self._lock_ctor(node.value)
                if got is None:
                    continue
                kind, reentrant = got
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        name = f"{module}.{t.id}"
                        self.locks[name] = LockDef(
                            name, kind, reentrant, fi.rel, node.lineno)
                        self.module_locks.setdefault(
                            module, {})[t.id] = name

    def _lock_ctor(self, value) -> Optional[Tuple[str, bool]]:
        if not isinstance(value, ast.Call):
            return None
        chain = _attr_chain(value.func)
        if not chain or chain[-1] not in _THREAD_CTORS:
            return None
        if chain[0] == _ASYNC_HEAD:
            return ("async", False)
        # bare Lock()/RLock() or threading.Lock(): the threading family
        return ("thread", chain[-1] == "RLock")

    def _resolve_lock(self, info: FuncInfo, expr) -> List[Tuple[str, str]]:
        """Lock names (name, chain-text) an acquisition expression may
        denote.  Handles module globals, self attrs (through the MRO)
        and attr chains typed by the index (`self.wal._lock`)."""
        chain = _attr_chain(expr)
        if not chain:
            return []
        text = ".".join(chain)
        if len(chain) == 1:
            got = self.module_locks.get(info.module, {}).get(chain[0])
            return [(got, text)] if got else []
        attr = chain[-1]
        out: List[Tuple[str, str]] = []
        recv = chain[:-1]
        if recv == ["self"] and info.cls is not None:
            for ci in self.idx.classes.get(info.cls, []):
                for c in self.idx.class_mro(ci):
                    got = self.class_locks.get(c.name, {}).get(attr)
                    if got:
                        out.append((got, text))
                        break
                if out:
                    break
            return out
        for t in sorted(self.idx._receiver_types(info, recv)):
            got = self.class_locks.get(t, {}).get(attr)
            if got:
                out.append((got, text))
        if not out:
            # module attr: mod._lock through imports
            head = self.idx.imports.get(info.module, {}).get(chain[0])
            if head and head[0] == "module":
                mod = ".".join([head[1]] + chain[1:-1])
                got = self.module_locks.get(mod, {}).get(attr)
                if got:
                    out.append((got, text))
        return out

    # ----------------------------------------------------- per-fn scanning

    def scan_all(self) -> None:
        for key, info in self.idx.funcs.items():
            if not info.module.startswith(self.prefix):
                continue
            self.scans[key] = self._scan_fn(info)

    def _scan_fn(self, info: FuncInfo) -> _FnScan:
        sc = _FnScan()
        held: List[_Held] = []

        def resolve_targets(call: ast.Call):
            return self.idx._resolve_call_targets(info, call.func)

        def on_acquire(names: List[Tuple[str, str]], via: str,
                       lineno: int) -> None:
            for name, chain in names:
                ld = self.locks[name]
                sc.acquires.add(name)
                if chain.startswith("self."):
                    sc.self_acquires.add(name)
                sc.events.append(("acq", name, via, chain, lineno,
                                  list(h.name for h in held)))
                held.append(_Held(name, ld.kind, via, chain))

        def on_release(names: List[Tuple[str, str]]) -> None:
            for name, _chain in names:
                for i in range(len(held) - 1, -1, -1):
                    if held[i].name == name:
                        del held[i]
                        break
                else:
                    sc.releases_on_entry.add(name)
                sc.events.append(("rel", name))

        def visit(node) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # nested scopes are their own FuncInfos
            if isinstance(node, (ast.With, ast.AsyncWith)):
                entered: List[Tuple[str, str]] = []
                for item in node.items:
                    ctx = item.context_expr
                    # `with lock:` or `with self._lock:` (strip a
                    # trailing .acquire-style call if written as one)
                    got = self._resolve_lock(info, ctx)
                    if got:
                        entered.extend(got)
                        continue
                    visit_expr(ctx)
                on_acquire(entered, "with", node.lineno)
                for child in node.body:
                    visit(child)
                on_release(list(reversed(entered)))
                return
            if isinstance(node, ast.Try):
                for child in node.body:
                    visit(child)
                for h in node.handlers:
                    for child in h.body:
                        visit(child)
                for child in node.orelse:
                    visit(child)
                for child in node.finalbody:
                    visit(child)
                return
            visit_expr(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        def visit_expr(node) -> None:
            if isinstance(node, ast.Await):
                sc.events.append(("await", node.lineno,
                                  [h.name for h in held
                                   if h.via != "with"],
                                  [h.name for h in held]))
                return
            if not isinstance(node, ast.Call):
                return
            chain = _attr_chain(node.func)
            attr = chain[-1] if chain else None
            if attr == "acquire" and chain is not None and len(chain) > 1:
                got = self._resolve_lock(
                    info, node.func.value if isinstance(
                        node.func, ast.Attribute) else None)
                if got and not _nonblocking(node):
                    on_acquire(got, "acquire", node.lineno)
                    return
            if attr == "release" and chain is not None and len(chain) > 1:
                got = self._resolve_lock(
                    info, node.func.value if isinstance(
                        node.func, ast.Attribute) else None)
                if got:
                    on_release(got)
                    return
            targets = resolve_targets(node)
            if targets:
                recv_self = bool(chain and chain[0] == "self"
                                 and len(chain) == 2)
                sc.events.append(("call",
                                  [t.key for t in targets],
                                  node.lineno,
                                  [h.name for h in held],
                                  recv_self))

        for child in ast.iter_child_nodes(info.node):
            visit(child)
        sc.holds_on_exit = {h.name for h in held if h.via == "acquire"}
        return sc

    # --------------------------------------------------------- summaries

    def summarize(self) -> None:
        """Transitive lock-acquisition summaries over CALL + EXECUTOR
        edges, to a fixed point."""
        out_edges: Dict[str, List[str]] = {}
        for e in self.idx.edges:
            if e.kind in (CALL, EXECUTOR):
                out_edges.setdefault(e.caller, []).append(e.callee)
        for key, sc in self.scans.items():
            self.summary[key] = set(sc.acquires)
            self.summary_self[key] = set(sc.self_acquires)
        changed = True
        while changed:
            changed = False
            for key in self.scans:
                s = self.summary[key]
                for callee in out_edges.get(key, ()):
                    cs = self.summary.get(callee)
                    if cs and not cs <= s:
                        s |= cs
                        changed = True
        # self-receiver summaries propagate only through self.m() calls
        changed = True
        while changed:
            changed = False
            for key, sc in self.scans.items():
                s = self.summary_self[key]
                for ev in sc.events:
                    if ev[0] != "call" or not ev[4]:
                        continue
                    for callee in ev[1]:
                        cs = self.summary_self.get(callee)
                        if cs and not cs <= s:
                            s |= cs
                            changed = True

    # ------------------------------------------------------------- edges

    def build_edges(self) -> None:
        for key, sc in self.scans.items():
            info = self.idx.funcs[key]
            fi = self.idx.files[info.path]
            # the role label makes the graph per-role: an edge held on
            # a loop-only function can only collide with worker-held
            # edges of the same pair, which is exactly the cross-thread
            # deadlock the cycle/inversion checks exist for
            role_s = "/".join(sorted(self.roles.get(key, ()))) or "?"
            for ev in sc.events:
                if ev[0] == "acq":
                    _tag, name, _via, chain, lineno, held = ev
                    ann = _lock_after(fi.annotations.get(lineno, ""))
                    for h in held:
                        if h == name:
                            self._check_reentry(info, name, chain,
                                                lineno)
                            continue
                        self.edges.append(LockEdge(
                            held=h, acquired=name, path=info.path,
                            line=lineno, func=info.qualname,
                            roles=role_s, blessed=(ann == h)))
                elif ev[0] == "call":
                    _tag, targets, lineno, held, recv_self = ev
                    if not held:
                        continue
                    ann = _lock_after(fi.annotations.get(lineno, ""))
                    acq: Set[str] = set()
                    for t in targets:
                        acq |= self.summary.get(t, set())
                    for h in held:
                        for name in sorted(acq):
                            if name == h:
                                if recv_self:
                                    self._check_reentry_hop(
                                        info, targets, name, lineno)
                                continue
                            self.edges.append(LockEdge(
                                held=h, acquired=name, path=info.path,
                                line=lineno, func=info.qualname,
                                roles=role_s, blessed=(ann == h)))

    def _check_reentry(self, info: FuncInfo, name: str, chain: str,
                       lineno: int) -> None:
        ld = self.locks[name]
        fi = self.idx.files[info.path]
        if ld.reentrant or ld.kind != "thread":
            return
        if not chain.startswith("self."):
            return  # distinct-instance acquisition is legal
        if lineno in fi.ignored_lines:
            return
        self.findings.append(Finding(
            code="lock-reentry", severity=ERROR, path=info.path,
            line=lineno,
            message=(
                f"{info.qualname} re-acquires non-reentrant lock "
                f"{name} already held on the same instance — "
                "guaranteed self-deadlock (use an RLock or hoist the "
                "outer acquisition)"
            ),
            ident=f"{info.qualname}:{name}",
        ))

    def _check_reentry_hop(self, info: FuncInfo, targets: List[str],
                           name: str, lineno: int) -> None:
        """`with self._lock: self.helper()` where helper re-acquires
        self._lock: same instance by construction."""
        ld = self.locks[name]
        fi = self.idx.files[info.path]
        if ld.reentrant or ld.kind != "thread":
            return
        if lineno in fi.ignored_lines:
            return
        if not any(name in self.summary_self.get(t, set())
                   for t in targets):
            return
        self.findings.append(Finding(
            code="lock-reentry", severity=ERROR, path=info.path,
            line=lineno,
            message=(
                f"{info.qualname} calls a self-method that re-acquires "
                f"non-reentrant lock {name} already held — guaranteed "
                "self-deadlock through the call-graph hop"
            ),
            ident=f"{info.qualname}:{name}:hop",
        ))

    # ----------------------------------------------------- graph analysis

    def check_cycles(self) -> None:
        graph: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], LockEdge] = {}
        for e in self.edges:
            if e.held == e.acquired:
                continue
            graph.setdefault(e.held, set()).add(e.acquired)
            sites.setdefault((e.held, e.acquired), e)
        for cyc in _cycles(graph):
            parts = []
            for a, b in zip(cyc, cyc[1:] + cyc[:1]):
                e = sites[(a, b)]
                parts.append(f"{a} -> {b} at {e.path}:{e.line} "
                             f"({e.func}, role {e.roles})")
            first = sites[(cyc[0], cyc[1] if len(cyc) > 1 else cyc[0])]
            self.findings.append(Finding(
                code="lock-cycle", severity=ERROR, path=first.path,
                line=first.line,
                message=(
                    "lock-order cycle (deadlock when the critical "
                    "sections overlap): " + "; ".join(parts)
                ),
                ident="/".join(cyc),
            ))

    def check_order(self, order: List[str]) -> None:
        pos = {name: i for i, name in enumerate(order)}
        for name in order:
            if name not in self.locks:
                ld_path = os.path.join("tools", "analysis",
                                       LOCKORDER_NAME)
                self.findings.append(Finding(
                    code="lockorder-dead", severity=WARN, path=ld_path,
                    line=1,
                    message=(
                        f"lockorder.json lists {name!r} but no such "
                        "lock exists in the tree — remove the stale "
                        "entry"
                    ),
                    ident=name,
                ))
        seen: Set[Tuple[str, str]] = set()
        for e in self.edges:
            if e.blessed or e.held == e.acquired:
                continue
            ih, ia = pos.get(e.held), pos.get(e.acquired)
            if ih is None or ia is None or ih < ia:
                continue
            fi = self.idx.files.get(e.path)
            if fi is not None and e.line in fi.ignored_lines:
                continue
            key = (e.held, e.acquired)
            if key in seen:
                continue
            seen.add(key)
            self.findings.append(Finding(
                code="lock-order", severity=ERROR, path=e.path,
                line=e.line,
                message=(
                    f"{e.func} (role {e.roles}) acquires {e.acquired} "
                    f"while holding {e.held}, inverting the blessed "
                    "global order "
                    f"({e.held} is #{ih}, {e.acquired} is #{ia} in "
                    "lockorder.json) — reorder the acquisitions, or "
                    f"annotate `# analysis: lock-after={e.held}` with "
                    "a justifying comment"
                ),
                ident=f"{e.held}>{e.acquired}",
            ))

    def check_await_hops(self) -> None:
        """`await` while a threading lock is held NON-lexically — via
        `.acquire()` in this function or a call into a holds-on-exit
        function.  The lexical `with` case is the races pass's."""
        for key, sc in self.scans.items():
            info = self.idx.funcs[key]
            if not info.is_async:
                continue
            fi = self.idx.files[info.path]
            held: List[str] = []
            for ev in sc.events:
                if ev[0] == "acq" and ev[2] == "acquire":
                    held.append(ev[1])
                elif ev[0] == "rel":
                    if ev[1] in held:
                        held.remove(ev[1])
                elif ev[0] == "call":
                    for t in ev[1]:
                        tsc = self.scans.get(t)
                        if tsc is None:
                            continue
                        for name in tsc.holds_on_exit:
                            held.append(name)
                        for name in tsc.releases_on_entry:
                            if name in held:
                                held.remove(name)
                elif ev[0] == "await":
                    _tag, lineno, _nonlex, _all = ev
                    bad = [n for n in held
                           if self.locks[n].kind == "thread"]
                    if not bad or lineno in fi.ignored_lines:
                        continue
                    self.findings.append(Finding(
                        code="await-under-lock-hop", severity=ERROR,
                        path=info.path, line=lineno,
                        message=(
                            f"await in {info.qualname} while threading "
                            f"lock {bad[0]} is held through a "
                            "non-lexical acquire (split begin()/end() "
                            "guard or bare .acquire()) — the coroutine "
                            "parks inside the critical section"
                        ),
                        ident=f"{info.qualname}:{bad[0]}",
                    ))
                    held = [n for n in held if n not in bad]

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict[str, int]:
        return {
            "locks": len(self.locks),
            "edges": len(self.edges),
            "edges_on_loop": sum(
                1 for e in self.edges if "loop" in e.roles),
            "edges_off_loop": sum(
                1 for e in self.edges
                if "worker" in e.roles or "pool" in e.roles),
            "functions_scanned": len(self.scans),
            "holds_on_exit_fns": sum(
                1 for sc in self.scans.values() if sc.holds_on_exit),
        }


def _nonblocking(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if node.args and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is False:
        return True
    return False


def _lock_after(ann: str) -> Optional[str]:
    if not ann.startswith("lock-after="):
        return None
    return ann[len("lock-after="):].split()[0].strip()


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles, one representative per SCC (Tarjan SCCs, then
    a shortest cycle inside each non-trivial component) — enough to
    report every deadlock family exactly once."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in graph and w not in index:
                index[w] = low[w] = counter[0]
                counter[0] += 1
                continue
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    out: List[List[str]] = []
    for comp in sccs:
        cset = set(comp)
        start = min(comp)
        # BFS shortest cycle through `start` within the SCC
        best: Optional[List[str]] = None
        queue: List[List[str]] = [[start]]
        while queue:
            path = queue.pop(0)
            v = path[-1]
            for w in sorted(graph.get(v, ())):
                if w == start and len(path) > 1:
                    best = path
                    queue = []
                    break
                if w in cset and w not in path:
                    queue.append(path + [w])
            if best:
                break
        out.append(best or comp)
    return out


def load_lockorder(path: str) -> List[str]:
    if not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("order", []))


def check_locks(
    idx: ProjectIndex,
    roles: Dict[str, Set[str]],
    package_prefix: str = "emqx_tpu",
    order: Optional[List[str]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    la = LockAnalysis(idx, roles, package_prefix)
    la.collect_locks()
    la.scan_all()
    la.summarize()
    la.build_edges()
    la.check_cycles()
    if order is None:
        order = load_lockorder(lockorder_path(idx.repo))
    la.check_order(order)
    la.check_await_hops()
    return la.findings, la.stats()
