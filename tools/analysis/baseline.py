"""Committed baseline for grandfathered warnings.

`baseline.json` (next to this module, committed) holds line-number-free
finding fingerprints.  On a run, a *warn*-tier finding whose
fingerprint is baselined is reported but does not fail the gate; a new
warning (not in the file) fails like an error.  Errors are NEVER
baselineable — the dialyzer ignore-file model: style/debt can be
grandfathered, contract violations cannot.

`--write-baseline` regenerates the file from the current run's
non-error findings (sorted, deduplicated) so the diff review shows
exactly which debts are being accepted.
"""

from __future__ import annotations

import json
import os
from typing import List, Set

from .report import ERROR, Report

BASELINE_NAME = "baseline.json"


def baseline_path(repo: str) -> str:
    return os.path.join(repo, "tools", "analysis", BASELINE_NAME)


def load_baseline(path: str) -> Set[str]:
    if not os.path.isfile(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return set(data.get("findings", []))


def apply_baseline(report: Report, fingerprints: Set[str]) -> None:
    for f in report.findings:
        if f.severity != ERROR and f.fingerprint in fingerprints:
            f.baselined = True


def write_baseline(report: Report, path: str) -> List[str]:
    fps = sorted({
        f.fingerprint for f in report.findings if f.severity != ERROR
    })
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "comment": (
                    "grandfathered static-analysis warnings; "
                    "regenerate with `python -m tools.analysis "
                    "--write-baseline` (errors are never baselined)"
                ),
                "findings": fps,
            },
            f, indent=2, sort_keys=True,
        )
        f.write("\n")
    return fps
