"""Pass (b): cross-thread state lint.

For every class in the package: collect `self.<attr>` writes and reads
per method, join with the thread-role classification of each method
(roles pass).  An attribute is *shared* when

* it is written from >= 2 distinct roles, or
* it is written off-loop (worker/pool) and read on-loop (or written
  on-loop and read off-loop);

and a shared attribute must be either

* guarded by ONE consistently-held `threading.Lock`-family attribute in
  every non-`__init__` access (`with self._lock:` lexically encloses
  the access), or
* annotated `# analysis: owner=<role>` on a line that mentions the
  attribute (typically its `__init__` assignment), asserting a
  deliberate single-owner / benign-race design with the justification
  in the surrounding comment.

`__init__`/`__new__` writes are construction (happens-before publish)
and contribute neither a role nor an unguarded access.  Methods with no
inferred role are unknown, not safe — they don't create multi-role
evidence, but an unguarded access in one does not clear a finding
either.

Also flagged here: `await` while a `threading.Lock` is held (`with
self._lock: ... await ...`) — the loop parks inside the critical
section and every worker contending on that lock stalls behind a
suspended coroutine.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .index import FuncInfo, ProjectIndex, _attr_chain
from .report import ERROR, Finding
from .roles import DELIVERY, LOOP

_CTOR_METHODS = {"__init__", "__new__", "__post_init__"}
_LOCK_CTORS = {"Lock", "RLock", "Condition"}


@dataclass
class _Access:
    method: str
    lineno: int
    is_write: bool
    locks: frozenset  # lock attr names held at this access


@dataclass
class _ClassState:
    lock_attrs: Set[str] = field(default_factory=set)
    accesses: Dict[str, List[_Access]] = field(default_factory=dict)
    owner_annotated: Dict[str, str] = field(default_factory=dict)


def check_races(
    idx: ProjectIndex,
    roles: Dict[str, Set[str]],
    package_prefix: str = "emqx_tpu",
) -> List[Finding]:
    findings: List[Finding] = []
    for cls_list in idx.classes.values():
        for ci in cls_list:
            if not ci.module.startswith(package_prefix):
                continue
            st = _collect_class(idx, ci)
            findings.extend(_judge_class(idx, ci, st, roles))
            findings.extend(_check_await_under_lock(idx, ci, st))
    return findings


def _collect_class(idx: ProjectIndex, ci) -> _ClassState:
    st = _ClassState()
    fi = idx.files[ci.path]
    # lock attributes: self.x = threading.Lock()/RLock()/Condition()
    for m in ci.methods.values():
        for node in ast.walk(m.node):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                chain = _attr_chain(v.func)
                if chain and chain[-1] in _LOCK_CTORS:
                    for t in node.targets:
                        tc = _attr_chain(t)
                        if tc and tc[0] == "self" and len(tc) == 2:
                            st.lock_attrs.add(tc[1])
    # owner annotations: "# analysis: owner=<role>" on a line that
    # mentions self.<attr> inside this class's span
    end = getattr(ci.node, "end_lineno", None) or ci.lineno
    for lineno, ann in fi.annotations.items():
        if not (ci.lineno <= lineno <= end):
            continue
        if not ann.startswith("owner="):
            continue
        role = ann[len("owner="):].split()[0].split("(")[0].strip()
        line = fi.lines[lineno - 1]
        # every self.<attr> mentioned on the annotated line
        try:
            expr = ast.parse(line.split("#", 1)[0].strip(), mode="exec")
        except SyntaxError:
            expr = None
        names = set()
        if expr is not None:
            for n in ast.walk(expr):
                if isinstance(n, ast.Attribute) and isinstance(
                    n.value, ast.Name
                ) and n.value.id == "self":
                    names.add(n.attr)
        for name in names:
            st.owner_annotated[name] = role
    # accesses per method, with the lexical lock-held set
    for m in ci.methods.values():
        _collect_accesses(m, st)
    return st


def _collect_accesses(m: FuncInfo, st: _ClassState) -> None:
    def visit(node, held: frozenset):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                chain = _attr_chain(item.context_expr)
                if chain and chain[0] == "self" and len(chain) == 2 \
                        and chain[1] in st.lock_attrs:
                    inner = inner | {chain[1]}
            for child in node.body:
                visit(child, inner)
            for item in node.items:
                visit(item.context_expr, held)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are their own functions
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "self":
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if node.attr not in st.lock_attrs:
                st.accesses.setdefault(node.attr, []).append(_Access(
                    method=m.qualname.split(".")[-1],
                    lineno=node.lineno,
                    is_write=is_write,
                    locks=held,
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for child in ast.iter_child_nodes(m.node):
        visit(child, frozenset())


def _judge_class(idx: ProjectIndex, ci, st: _ClassState,
                 roles: Dict[str, Set[str]]) -> List[Finding]:
    findings: List[Finding] = []
    method_roles: Dict[str, Set[str]] = {}
    for name, m in ci.methods.items():
        r = set(roles.get(m.key, set()))
        # DELIVERY labels loop-side work (asyncio delivery-shard
        # workers) — same OS thread as LOOP, so it is not a distinct
        # writer for the cross-THREAD race join
        if DELIVERY in r:
            r.discard(DELIVERY)
            r.add(LOOP)
        method_roles[name] = r
    fi = idx.files[ci.path]
    for attr, accesses in sorted(st.accesses.items()):
        write_roles: Set[str] = set()
        read_roles: Set[str] = set()
        for a in accesses:
            if a.method in _CTOR_METHODS:
                continue
            r = method_roles.get(a.method, set())
            if a.is_write:
                write_roles |= r
            else:
                read_roles |= r
        shared = (
            len(write_roles) >= 2
            or (write_roles - {LOOP} and LOOP in read_roles)
            or (LOOP in write_roles and read_roles - {LOOP})
        )
        if not shared:
            continue
        if attr in st.owner_annotated:
            continue  # deliberate; justification lives at the annotation
        # consistently-locked: every non-ctor access holds one common lock
        locked = [
            a for a in accesses if a.method not in _CTOR_METHODS
        ]
        common = None
        for a in locked:
            common = set(a.locks) if common is None else common & a.locks
            if not common:
                break
        if common:
            continue
        unguarded = [a for a in locked if not a.locks]
        where = unguarded[0] if unguarded else locked[0]
        if where.lineno in fi.ignored_lines:
            continue
        wr = ",".join(sorted(write_roles)) or "?"
        rd = ",".join(sorted(read_roles)) or "?"
        findings.append(Finding(
            code="race", severity=ERROR, path=ci.path,
            line=where.lineno,
            message=(
                f"{ci.name}.{attr} is written from role(s) [{wr}] and "
                f"read from [{rd}] without a consistently-held "
                "threading.Lock — guard every access with one lock or "
                "annotate the attribute `# analysis: owner=<role>` with "
                "a justifying comment"
            ),
            ident=f"{ci.name}.{attr}",
        ))
    return findings


def _check_await_under_lock(idx: ProjectIndex, ci,
                            st: _ClassState) -> List[Finding]:
    findings: List[Finding] = []
    fi = idx.files[ci.path]
    for m in ci.methods.values():
        if not m.is_async:
            continue

        def visit(node, held: Optional[str]):
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    chain = _attr_chain(item.context_expr)
                    if chain and chain[0] == "self" and len(chain) == 2 \
                            and chain[1] in st.lock_attrs:
                        inner = chain[1]
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, ast.Await) and held is not None \
                    and node.lineno not in fi.ignored_lines:
                findings.append(Finding(
                    code="await-under-lock", severity=ERROR,
                    path=ci.path, line=node.lineno,
                    message=(
                        f"await while holding threading lock "
                        f"self.{held} in {ci.name}."
                        f"{m.qualname.split('.')[-1]} — the coroutine "
                        "can suspend inside the critical section and "
                        "stall every thread contending on the lock"
                    ),
                    ident=f"{ci.name}.{m.qualname.split('.')[-1]}:{held}",
                ))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in ast.iter_child_nodes(m.node):
            visit(child, None)
    return findings
