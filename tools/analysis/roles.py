"""Pass (a): thread-role inference + event-loop blocking-call detector.

Dialyzer infers success typings from known roots; this pass infers
*thread roles* the same way.  Roots:

* every `async def` body runs on the event loop -> role ``loop``;
* targets of `asyncio.to_thread` / `loop.run_in_executor` /
  `threading.Thread(target=...)` run on a worker thread -> ``worker``
  (the hop CLEARS the caller's loop role — that is the whole point of
  the hop);
* functions in `ops/native.py` that enter the GIL-free C++ worker pool
  (any `lib.etpu_*` call) additionally carry ``pool``;
* `create_task`/`ensure_future` targets stay ``loop``;
* async methods of the delivery-worker pool (`broker/delivery.py`
  DeliveryPool) additionally carry ``delivery`` — still loop-side, the
  label just names the plane a blocking call would stall (one blocked
  shard worker head-of-line-blocks its whole fan-out shard).

Roles propagate caller -> callee over plain call edges to a fixed
point.  A function whose role set contains ``loop`` is reachable on the
event loop without an intervening executor hop; a *blocking primitive*
inside it stalls every connection, heartbeat and timer on the node —
exactly the PR 4 fix #3 (`time.sleep` fault action freezing the loop)
and PR 5 fix #2 (fsync-heavy GC on the wrong thread) class of bug.

Severity: ``error`` when the function is reachable ONLY on the loop
(no worker/pool path exists — the call definitely blocks the loop);
``warn`` when the function is multi-role (a loop path exists among
others; possibly the loop caller is a shutdown/test convenience).

Suppression: `# analysis: allow-blocking(<reason>)` on the offending
line — the reason is mandatory, an empty one is itself a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .index import CALL, EXECUTOR, FuncInfo, ProjectIndex, \
    _attr_chain, _walk_own_body
from .report import ERROR, WARN, Finding

LOOP = "loop"
WORKER = "worker"
POOL = "pool"
# delivery-shard workers (broker/delivery.py DeliveryPool): asyncio
# tasks draining the per-shard fan-out queues.  They run ON the loop
# (so LOOP-blocking findings apply with full force), but carry their
# own role label so a finding inside the broadcast drain path names
# the plane it stalls — one blocked shard worker head-of-line-blocks
# its whole fan-out shard.
DELIVERY = "delivery"

# wire-worker process entry points (emqx_tpu/wire/worker.py): code in
# these modules runs in a CHILD OS process spawned by the wire
# supervisor.  The label itself is informational (a separate process
# has its own loop/GIL); the teeth are `check_proc_boundary` below —
# cross-process `self.<attr>` sharing is impossible exactly as long as
# neither side ever imports the other, so only transport frames (and
# the spawn command line / config file / inherited fds) cross.
PROC = "proc"

# (module, class) roots whose async methods seed the DELIVERY role
_DELIVERY_ROOTS = {("emqx_tpu.broker.delivery", "DeliveryPool")}

# modules whose code runs ONLY in a wire-worker child process
_PROC_ENTRY_MODULES = {"emqx_tpu.wire.worker"}
# modules whose objects live ONLY in the parent/supervisor process
_PARENT_ONLY_MODULES = {"emqx_tpu.wire.supervisor"}

# the ONE blessed shared-state crossing of the wire-worker process
# boundary: the shm match plane (`emqx_tpu/shm/`).  Its rings carry
# fixed-layout records through seqlock'd slots — every other module
# must keep to transport frames, so any other import of
# `multiprocessing.shared_memory` is an unreviewed process crossing.
_SHM_BLESSED_PREFIX = "emqx_tpu.shm"

# module-level blocking primitives: (head name, attr)
_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("os", "fsync"),
    ("os", "fdatasync"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"),
    ("socket", "getaddrinfo"),
    ("socket", "gethostbyname"),
}

# attr calls blocking when the receiver is file-like (bound from open())
_FILEISH_METHODS = {"write", "flush", "read", "readline", "readlines",
                    "truncate", "seek"}
# attr calls blocking when the receiver is socket-like
_SOCKISH_METHODS = {"recv", "send", "sendall", "accept", "connect",
                    "makefile"}


def infer_roles(idx: ProjectIndex) -> Dict[str, Set[str]]:
    roles: Dict[str, Set[str]] = {}

    def add(key: str, role: str) -> bool:
        s = roles.setdefault(key, set())
        if role in s:
            return False
        s.add(role)
        return True

    # roots
    for key, info in idx.funcs.items():
        if info.is_async:
            add(key, LOOP)
            if (info.module, info.cls) in _DELIVERY_ROOTS:
                add(key, DELIVERY)
        if info.module in _PROC_ENTRY_MODULES:
            add(key, PROC)
        if info.module == "emqx_tpu.ops.native" and _enters_native_pool(
            info
        ):
            add(key, POOL)
    for e in idx.edges:
        if e.kind == EXECUTOR and e.callee in idx.funcs:
            add(e.callee, WORKER)

    # propagate over plain call edges to a fixed point
    out_edges: Dict[str, List] = {}
    for e in idx.edges:
        if e.kind == CALL:
            out_edges.setdefault(e.caller, []).append(e.callee)
    changed = True
    while changed:
        changed = False
        for caller, callees in out_edges.items():
            src = roles.get(caller)
            if not src:
                continue
            for callee in callees:
                info = idx.funcs.get(callee)
                if info is None:
                    continue
                # an async callee runs on the loop regardless of who
                # schedules it; don't smear the caller's roles onto it
                if info.is_async:
                    continue
                for r in src:
                    # PROC never propagates: it labels the worker
                    # PROCESS's entry module, not a thread — shared
                    # broker code called from a worker entry point runs
                    # in that process under its own loop/worker roles,
                    # and smearing `proc` across the call graph would
                    # fabricate cross-"thread" races between what are
                    # really two address spaces
                    if r == PROC:
                        continue
                    changed |= add(callee, r)
    return roles


def _enters_native_pool(info: FuncInfo) -> bool:
    for node in _walk_own_body(info.node):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and len(chain) >= 2 and chain[0] in ("lib", "_lib") \
                    and chain[-1].startswith("etpu_"):
                return True
    return False


# ------------------------------------------------------------ detection


def check_blocking(
    idx: ProjectIndex,
    roles: Dict[str, Set[str]],
    package_prefix: str = "emqx_tpu",
) -> List[Finding]:
    findings: List[Finding] = []
    for key, info in idx.funcs.items():
        if not info.module.startswith(package_prefix):
            continue
        fn_roles = roles.get(key, set())
        if LOOP not in fn_roles:
            continue
        # "pure loop" = no executor/pool path exists; DELIVERY is a
        # loop-side label, not an escape hatch, so it must not soften
        # the severity
        pure_loop = not (fn_roles & {WORKER, POOL})
        fi = idx.files[info.path]
        file_vars = _fileish_names(idx, info)
        sock_vars = _sockish_names(idx, info)
        lock_vars = _lockish_names(idx, info)
        event_vars = _eventish_names(idx, info)
        for node in _walk_own_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            desc = _blocking_desc(
                idx, info, node, file_vars, sock_vars, lock_vars,
                event_vars,
            )
            if desc is None:
                continue
            line = node.lineno
            if line in fi.ignored_lines:
                continue
            ann = fi.annotations.get(line, "")
            if ann.startswith("allow-blocking"):
                reason = ann[len("allow-blocking"):].strip("(): ")
                if reason:
                    continue
                findings.append(Finding(
                    code="block-annotation", severity=ERROR,
                    path=info.path, line=line,
                    message=(
                        "allow-blocking annotation without a reason "
                        "(write `# analysis: allow-blocking(<why>)`)"
                    ),
                    ident=f"{info.qualname}:{desc}",
                ))
                continue
            role_s = "/".join(sorted(fn_roles))
            findings.append(Finding(
                code="block", severity=ERROR if pure_loop else WARN,
                path=info.path, line=line,
                message=(
                    f"{desc} in {info.qualname} (role: {role_s}) "
                    "blocks the event loop — move it behind "
                    "asyncio.to_thread/run_in_executor or annotate "
                    "`# analysis: allow-blocking(<why>)`"
                ),
                ident=f"{info.qualname}:{desc}",
            ))
    return findings


def check_proc_boundary(
    idx: ProjectIndex, package_prefix: str = "emqx_tpu",
) -> List[Finding]:
    """The PROC-role process-boundary lint.

    A wire worker is a separate OS process: any `self.<attr>` (or plain
    object) the supervisor and a worker both "share" is actually two
    unrelated copies, and code that compiles against the other side's
    classes is wrong by construction — the write lands in one process,
    the read happens in the other.  Python can't share state that was
    never imported, so the enforceable invariant is exactly that:

    * no production module may import a PROC entry module
      (`emqx_tpu.wire.worker`) — parent-side code holding worker-side
      objects is cross-process state sharing, and importing the worker
      module into the parent is the only way to get one;
    * a PROC entry module may not import a parent-only module
      (`emqx_tpu.wire.supervisor`) — the symmetric direction;
    * call edges across the same boundary pairs are errors too (they
      catch indirect access through re-exports the import check might
      attribute to an innocent package module).

    Only transport messages cross the boundary; tests/tools/bench are
    exempt (they orchestrate both sides from the outside).
    """
    findings: List[Finding] = []

    def _target_module(imp: tuple) -> str:
        # ("module", name) or ("symbol", module, symbol)
        return imp[1] if len(imp) > 1 else ""

    def _hits(target: str, pool: set) -> bool:
        return any(
            target == m or target.startswith(m + ".") for m in pool
        )

    for mod, imports in sorted(idx.imports.items()):
        if not mod.startswith(package_prefix):
            continue
        fi = next(
            (f for f in idx.files.values() if f.module == mod), None
        )
        rel = fi.rel if fi is not None else mod
        for _local, imp in sorted(imports.items()):
            target = _target_module(imp)
            if mod not in _PROC_ENTRY_MODULES and _hits(
                target, _PROC_ENTRY_MODULES
            ):
                findings.append(Finding(
                    code="proc-boundary", severity=ERROR, path=rel,
                    line=1,
                    message=(
                        f"{mod} imports worker-process module "
                        f"{target!r}: wire workers are separate OS "
                        "processes — cross-process self.<attr> sharing "
                        "is an error; only transport messages cross "
                        "the boundary"
                    ),
                    ident=f"{mod}->{target}",
                ))
            if mod in _PROC_ENTRY_MODULES and _hits(
                target, _PARENT_ONLY_MODULES
            ):
                findings.append(Finding(
                    code="proc-boundary", severity=ERROR, path=rel,
                    line=1,
                    message=(
                        f"worker-process module {mod} imports "
                        f"supervisor-side module {target!r}: parent "
                        "state does not exist in the worker process — "
                        "only transport messages cross the boundary"
                    ),
                    ident=f"{mod}->{target}",
                ))
    # call edges across the boundary (indirect sharing through
    # re-exports): a resolved callee carries its defining module
    for e in idx.edges:
        if e.kind != CALL:
            continue
        caller = idx.funcs.get(e.caller)
        callee = idx.funcs.get(e.callee)
        if caller is None or callee is None:
            continue
        pair = None
        if caller.module in _PROC_ENTRY_MODULES and \
                callee.module in _PARENT_ONLY_MODULES:
            pair = (caller, callee, "supervisor-side")
        elif callee.module in _PROC_ENTRY_MODULES and \
                caller.module.startswith(package_prefix) and \
                caller.module not in _PROC_ENTRY_MODULES:
            pair = (caller, callee, "worker-process")
        if pair is not None:
            c, t, side = pair
            findings.append(Finding(
                code="proc-boundary", severity=ERROR, path=c.path,
                line=c.node.lineno,
                message=(
                    f"{c.qualname} calls {side} function "
                    f"{t.qualname} across the wire-worker process "
                    "boundary — only transport messages cross"
                ),
                ident=f"{c.qualname}->{t.qualname}",
            ))
    return findings


def check_shm_blessing(
    idx: ProjectIndex, package_prefix: str = "emqx_tpu",
) -> List[Finding]:
    """`multiprocessing.shared_memory` is the ONE blessed PROC crossing.

    Shared memory IS cross-process state sharing — exactly what
    `check_proc_boundary` exists to forbid — so it gets a single
    reviewed enclave: `emqx_tpu/shm/`, whose ring layout (seqlock'd
    slots, generation stamps, cursor control page) makes the sharing
    crash-safe by construction.  Any other production module importing
    `multiprocessing.shared_memory` (module or symbol form) reopens the
    boundary without those invariants, so it is an error here.
    Tests/tools/bench stay exempt (they orchestrate both sides).

    The same rule pins the shm doorbell transport: `os.eventfd` /
    `os.eventfd_write` / `os.eventfd_read` are the wakeup side-channel
    of the ring protocol (armed-word handshake in shm/doorbell.py, fd
    inheritance via the supervisor's pass_fds), so any eventfd call in
    a production module outside `emqx_tpu/shm/` (the C side lives in
    `native/drain.cc`) is an unreviewed wakeup path and errors too.
    """
    findings: List[Finding] = []
    findings.extend(_check_eventfd_blessing(idx, package_prefix))
    for mod, imports in sorted(idx.imports.items()):
        if not mod.startswith(package_prefix):
            continue
        if mod == _SHM_BLESSED_PREFIX or mod.startswith(
            _SHM_BLESSED_PREFIX + "."
        ):
            continue
        fi = next(
            (f for f in idx.files.values() if f.module == mod), None
        )
        rel = fi.rel if fi is not None else mod
        for _local, imp in sorted(imports.items()):
            target = imp[1] if len(imp) > 1 else ""
            hit = target == "multiprocessing.shared_memory" or \
                target.startswith("multiprocessing.shared_memory.") or (
                    target == "multiprocessing" and len(imp) > 2
                    and imp[2] == "shared_memory"
                )
            if not hit:
                continue
            findings.append(Finding(
                code="shm-blessing", severity=ERROR, path=rel, line=1,
                message=(
                    f"{mod} imports multiprocessing.shared_memory "
                    "outside the blessed emqx_tpu.shm package — shared "
                    "memory is the one reviewed process crossing; go "
                    "through shm/registry.py + shm/rings.py instead"
                ),
                ident=f"{mod}->shared_memory",
            ))
    return findings


_EVENTFD_NAMES = {"eventfd", "eventfd_write", "eventfd_read"}


def _check_eventfd_blessing(
    idx: ProjectIndex, package_prefix: str,
) -> List[Finding]:
    """Flag eventfd construction/use outside the shm enclave (the
    doorbell half of the shm-blessing rule — see check_shm_blessing)."""
    findings: List[Finding] = []
    for rel in sorted(idx.files):
        fi = idx.files[rel]
        mod = fi.module
        if not mod.startswith(package_prefix):
            continue
        if mod == _SHM_BLESSED_PREFIX or mod.startswith(
            _SHM_BLESSED_PREFIX + "."
        ):
            continue
        if fi.tree is None:
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            # os.eventfd*(...) or a bare eventfd*(...) pulled in via
            # `from os import eventfd...`
            hit = (len(chain) == 2 and chain[0] == "os"
                   and chain[1] in _EVENTFD_NAMES) or (
                len(chain) == 1 and chain[0] in _EVENTFD_NAMES)
            if not hit or node.lineno in fi.ignored_lines:
                continue
            findings.append(Finding(
                code="shm-blessing", severity=ERROR, path=rel,
                line=node.lineno,
                message=(
                    f"{mod} calls {'.'.join(chain)} outside the "
                    "blessed emqx_tpu.shm package — eventfd doorbells "
                    "are part of the reviewed ring protocol; go "
                    "through shm/doorbell.py instead"
                ),
                ident=f"{mod}->{chain[-1]}",
            ))
    return findings


def _blocking_desc(
    idx: ProjectIndex, info: FuncInfo, node: ast.Call,
    file_vars: Set[str], sock_vars: Set[str], lock_vars: Set[str],
    event_vars: Set[str],
) -> Optional[str]:
    chain = _attr_chain(node.func)
    if not chain:
        return None
    if len(chain) == 2 and tuple(chain) in _BLOCKING_MODULE_CALLS:
        return f"{chain[0]}.{chain[1]}()"
    attr = chain[-1]
    recv = ".".join(chain[:-1])
    if attr in _FILEISH_METHODS and recv in file_vars:
        return f"file {recv}.{attr}()"
    if attr in _SOCKISH_METHODS and recv in sock_vars:
        return f"socket {recv}.{attr}()"
    if attr == "acquire" and (recv in lock_vars or "lock" in recv.lower()):
        if not _nonblocking_acquire(node):
            return f"blocking {recv}.acquire()"
    if attr == "wait" and recv in event_vars:
        return f"threading.Event {recv}.wait()"
    return None


def _nonblocking_acquire(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == 0:
            return True
        if kw.arg == "blocking" and isinstance(kw.value, ast.Name):
            return True  # acquire(blocking=flag): caller decides
    if node.args and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is False:
        return True
    return False


def _bound_from(idx: ProjectIndex, info: FuncInfo, match) -> Set[str]:
    """Receiver names (locals, `with ... as x`, self.attr dotted paths)
    bound from a constructor the `match(call_node)` predicate accepts —
    scanning this function AND, for self attrs, every method of the
    enclosing class."""
    out: Set[str] = set()

    def scan(fn_node, allow_self: bool):
        for n in ast.walk(fn_node):
            value = None
            targets = []
            if isinstance(n, ast.Assign):
                value, targets = n.value, n.targets
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                value, targets = n.value, [n.target]
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None and match(
                        item.context_expr
                    ):
                        chain = _attr_chain(item.optional_vars)
                        if chain:
                            out.add(".".join(chain))
                continue
            if value is None or not match(value):
                continue
            for t in targets:
                chain = _attr_chain(t)
                if chain is None:
                    continue
                if chain[0] == "self" and not allow_self:
                    continue
                out.add(".".join(chain))

    scan(info.node, allow_self=True)
    if info.cls is not None:
        for ci in idx.classes.get(info.cls, []):
            if ci.module != info.module:
                continue
            for m in ci.methods.values():
                scan(m.node, allow_self=True)
    return out


def _ctor_match(*names: str):
    def match(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func)
        return bool(chain) and chain[-1] in names
    return match


def _fileish_names(idx: ProjectIndex, info: FuncInfo) -> Set[str]:
    return _bound_from(idx, info, _ctor_match("open"))


def _sockish_names(idx: ProjectIndex, info: FuncInfo) -> Set[str]:
    return _bound_from(
        idx, info, _ctor_match("socket", "create_connection")
    )


def _lockish_names(idx: ProjectIndex, info: FuncInfo) -> Set[str]:
    return _bound_from(
        idx, info, _ctor_match("Lock", "RLock", "Condition", "Semaphore",
                               "BoundedSemaphore")
    )


def _eventish_names(idx: ProjectIndex, info: FuncInfo) -> Set[str]:
    # only threading.Event (asyncio.Event.wait is awaited, not called)
    def match(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func)
        if not chain or chain[-1] != "Event":
            return False
        return chain[0] == "threading" or len(chain) == 1
    return _bound_from(idx, info, match)
