"""Pass (g): cancellation safety.

`asyncio.CancelledError` derives from `BaseException` precisely so
`except Exception` cannot eat it — but `except BaseException`, a bare
`except`, and an explicit `except (CancelledError, ...)` all can.  A
loop-role coroutine that swallows cancellation without re-raising turns
`task.cancel()` into a no-op: shutdown hangs waiting on a task that
"handled" its own death, or worse, the task keeps running against
half-torn-down state.  The complementary hazard: paired state mutation
around an `await` with no `finally` — a cancellation landing at the
await point leaks the first half of the pair (a counter never
decremented, a slot never released) because cancellation *is* an
exception raised at the await.

Checks (both on `async def` bodies — CancelledError is only ever
raised at an await point, so loop-role coroutines are exactly the
exposed surface):

* ``cancel-swallow`` (error): an except handler that catches
  CancelledError (bare, ``BaseException``, or an explicit tuple
  member) and neither re-raises nor returns the exception outward.
  The one blessed shape is the *reap* idiom — ``t.cancel()`` followed
  by ``try: await t except (CancelledError, Exception): pass`` — where
  the cancellation was initiated by this very function on the task it
  is awaiting; the pass traces ``.cancel()`` calls in the function and
  recognizes the join.  `contextlib.suppress(CancelledError)` around
  such a join is equally blessed; anywhere else it is the same bug.
* ``cancel-leak`` (error): in one statement block, a retained mutation
  (``self.x += 1``, ``.add``/``.append``/``.acquire``) followed by an
  ``await`` and then the inverse mutation (``-=``, ``.discard``/
  ``.remove``/``.pop``/``.release``) with the await outside any
  ``try/finally`` that performs the inverse — the worker-drain shape
  where a cancellation between the pair strands the state forever.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .index import FuncInfo, ProjectIndex, _attr_chain, _walk_own_body
from .report import ERROR, Finding

# inverse-mutation verb pairs for the cancel-leak check
_PAIR_VERBS = {
    "add": {"discard", "remove", "pop", "clear"},
    "append": {"remove", "pop", "clear"},
    "acquire": {"release"},
    "put_nowait": {"get_nowait", "task_done"},
}


def check_cancellation(
    idx: ProjectIndex,
    roles: Dict[str, Set[str]],
    package_prefix: str = "emqx_tpu",
) -> Tuple[List[Finding], Dict[str, int]]:
    findings: List[Finding] = []
    n_handlers = 0
    n_pairs = 0
    for key, info in idx.funcs.items():
        if not info.module.startswith(package_prefix):
            continue
        if not info.is_async:
            continue
        fi = idx.files[info.path]
        cancelled = _cancelled_chains(info)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Try):
                for h in node.handlers:
                    if not _catches_cancelled(h):
                        continue
                    n_handlers += 1
                    f = _judge_handler(info, fi, node, h, cancelled)
                    if f is not None:
                        findings.append(f)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                f = _judge_suppress(info, fi, node, cancelled)
                if f is not None:
                    findings.append(f)
        got, pairs = _check_pairs(info, fi)
        findings.extend(got)
        n_pairs += pairs
    return findings, {
        "cancelled_handlers": n_handlers,
        "mutation_pairs": n_pairs,
    }


# ------------------------------------------------------------ swallowing


def _catches_cancelled(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True  # bare except
    return any(_is_cancelled_type(t) or _is_base_exception(t)
               for t in _handler_types(h))


def _handler_types(h: ast.ExceptHandler):
    if isinstance(h.type, ast.Tuple):
        return list(h.type.elts)
    return [h.type] if h.type is not None else []


def _is_cancelled_type(t) -> bool:
    chain = _attr_chain(t)
    return bool(chain) and chain[-1] == "CancelledError"


def _is_base_exception(t) -> bool:
    chain = _attr_chain(t)
    return bool(chain) and chain[-1] == "BaseException"


def _reraises(h: ast.ExceptHandler) -> bool:
    for node in _walk_own_body(h):
        if isinstance(node, ast.Raise):
            return True
    return False


def _cancelled_chains(info: FuncInfo) -> Set[str]:
    """Attr-chain texts `.cancel()` is called on anywhere in this
    function — the tasks whose cancellation THIS function initiated."""
    out: Set[str] = set()
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "cancel" and len(chain) > 1:
                out.add(".".join(chain[:-1]))
    return out


def _awaited_chains(body) -> Optional[List[str]]:
    """If every statement in `body` is (just) an await of a simple
    chain, return those chains; else None."""
    out: List[str] = []
    for stmt in body:
        value = None
        if isinstance(stmt, ast.Expr):
            value = stmt.value
        elif isinstance(stmt, ast.Assign):
            value = stmt.value
        if not isinstance(value, ast.Await):
            return None
        chain = _attr_chain(value.value)
        if chain is None:
            # await asyncio.wait_for(t, ...) / gather(*ts): treat the
            # first simple-arg chain as the join target
            if isinstance(value.value, ast.Call):
                inner = [
                    ".".join(c) for c in (
                        _attr_chain(a) for a in value.value.args
                    ) if c
                ]
                if inner:
                    out.extend(inner)
                    continue
            return None
        out.append(".".join(chain))
    return out if out else None


def _is_reap(try_node: ast.Try, cancelled: Set[str]) -> bool:
    chains = _awaited_chains(try_node.body)
    if not chains:
        return False
    return all(c in cancelled for c in chains)


def _judge_handler(info: FuncInfo, fi, try_node: ast.Try,
                   h: ast.ExceptHandler,
                   cancelled: Set[str]) -> Optional[Finding]:
    if h.lineno in fi.ignored_lines:
        return None
    if _reraises(h):
        return None
    if _is_reap(try_node, cancelled):
        return None
    what = "bare except" if h.type is None else (
        "except BaseException"
        if any(_is_base_exception(t) for t in _handler_types(h))
        else "except CancelledError"
    )
    return Finding(
        code="cancel-swallow", severity=ERROR, path=info.path,
        line=h.lineno,
        message=(
            f"{what} in {info.qualname} swallows CancelledError "
            "without re-raising: task.cancel() on this coroutine "
            "becomes a no-op and shutdown can hang on it — re-raise "
            "cancellation (`except asyncio.CancelledError: raise`) or "
            "narrow the handler to `except Exception`"
        ),
        ident=f"{info.qualname}:{what}",
    )


def _judge_suppress(info: FuncInfo, fi, node,
                    cancelled: Set[str]) -> Optional[Finding]:
    for item in node.items:
        ctx = item.context_expr
        if not isinstance(ctx, ast.Call):
            continue
        chain = _attr_chain(ctx.func)
        if not chain or chain[-1] != "suppress":
            continue
        if not any(_is_cancelled_type(a) or _is_base_exception(a)
                   for a in ctx.args):
            continue
        if node.lineno in fi.ignored_lines:
            return None
        chains = _awaited_chains(node.body)
        if chains and all(c in cancelled for c in chains):
            return None  # reap via contextlib.suppress
        return Finding(
            code="cancel-swallow", severity=ERROR, path=info.path,
            line=node.lineno,
            message=(
                f"contextlib.suppress(CancelledError) in "
                f"{info.qualname} outside the cancel-then-join idiom "
                "swallows cancellation — suppress Exception instead, "
                "or cancel the awaited task in this function first"
            ),
            ident=f"{info.qualname}:suppress",
        )
    return None


# -------------------------------------------------------- mutation pairs


def _mutations(stmt) -> List[Tuple[str, str]]:
    """(chain, verb) mutations a statement performs at its top level:
    `self.n += 1` -> (self.n, +=) ; `self.s.add(x)` -> (self.s, add)."""
    out: List[Tuple[str, str]] = []
    if isinstance(stmt, ast.AugAssign):
        chain = _attr_chain(stmt.target)
        if chain:
            op = "+=" if isinstance(stmt.op, ast.Add) else (
                "-=" if isinstance(stmt.op, ast.Sub) else "")
            if op:
                out.append((".".join(chain), op))
    value = stmt.value if isinstance(stmt, ast.Expr) else None
    if isinstance(value, ast.Call):
        chain = _attr_chain(value.func)
        if chain and len(chain) > 1:
            out.append((".".join(chain[:-1]), chain[-1]))
    return out


def _has_await(stmt) -> bool:
    if isinstance(stmt, ast.Await):
        return True
    for node in _walk_own_body(stmt):
        if isinstance(node, ast.Await):
            return True
    return False


def _finally_inverse(stmt, chain: str, inverses: Set[str]) -> bool:
    """stmt is a Try whose finalbody performs an inverse mutation on
    `chain` — the protected shape."""
    if not isinstance(stmt, ast.Try):
        return False
    for fstmt in stmt.finalbody:
        for c, verb in _mutations(fstmt):
            if c == chain and verb in inverses:
                return True
    return False


def _inverses_of(verb: str) -> Set[str]:
    if verb == "+=":
        return {"-="}
    return _PAIR_VERBS.get(verb, set())


def _check_pairs(info: FuncInfo, fi) -> Tuple[List[Finding], int]:
    findings: List[Finding] = []
    pairs = 0

    def scan_block(body: List) -> None:
        nonlocal pairs
        # open mutations awaiting their inverse: chain -> (verb, line)
        open_muts: Dict[str, Tuple[str, int]] = {}
        awaited_since: Dict[str, int] = {}  # chain -> await line
        for stmt in body:
            if isinstance(stmt, ast.Try):
                # a try with a finally that closes an open pair
                # protects it; account for that, then recurse
                for chain in list(open_muts):
                    verb, line = open_muts[chain]
                    if _finally_inverse(stmt, chain,
                                        _inverses_of(verb)):
                        del open_muts[chain]
                        awaited_since.pop(chain, None)
            muts = _mutations(stmt)
            for chain, verb in muts:
                inv = _inverses_of(verb)
                closed = False
                for oc, (overb, oline) in list(open_muts.items()):
                    if oc == chain and verb in _inverses_of(overb):
                        aw = awaited_since.get(chain)
                        if aw is not None \
                                and oline not in fi.ignored_lines:
                            pairs += 1
                            findings.append(Finding(
                                code="cancel-leak", severity=ERROR,
                                path=info.path, line=aw,
                                message=(
                                    f"{info.qualname} mutates "
                                    f"{chain} ({overb} at line "
                                    f"{oline}) before an await and "
                                    f"reverts it ({verb}) after, with "
                                    "no try/finally — a cancellation "
                                    "landing at the await leaks the "
                                    "mutation forever; wrap the await "
                                    "in try/finally with the inverse "
                                    "in the finally"
                                ),
                                ident=f"{info.qualname}:{chain}",
                            ))
                        del open_muts[oc]
                        awaited_since.pop(chain, None)
                        closed = True
                        break
                if not closed and _inverses_of(verb):
                    open_muts[chain] = (verb, stmt.lineno)
                    awaited_since.pop(chain, None)
            if _has_await(stmt) and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a try/finally-wrapped await is protected for every
                # chain its finally reverts (handled above); for open
                # chains it is the hazard point
                for chain in open_muts:
                    awaited_since.setdefault(chain, stmt.lineno)
            # recurse into nested blocks with a fresh window (pairs
            # split across sibling blocks are a different shape)
            for child_body in _child_blocks(stmt):
                scan_block(child_body)

    scan_block(info.node.body)
    return findings, pairs


def _child_blocks(stmt) -> List[List]:
    out: List[List] = []
    for field_name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field_name, None)
        if isinstance(b, list) and b and not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            out.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out
