"""Pass (c): registry cross-checks, generalized — the xref analog.

xref proves every remote call lands on an exported function AND that
every export is called; this pass does both directions for every
name-registry the broker keys runtime behavior on:

* **config**: every literal `*.get("ns.key")` in the package must name
  a key declared in `config/config.py` SCHEMA (read => declared: a key
  read but never declared always resolves to the fallback and silently
  disables what it configures), and every declared key must be read
  somewhere in emqx_tpu/tools/bench (declared => read: silent no-op
  config is worse than missing config).  Namespace-wide reads
  (`conf.get("mqtt")` + `m["max_inflight"]` subscripts) and f-string
  reads (`conf.get(f"event_message.{k}")`) are tracked; a dynamic read
  marks the namespace covered for the dead-key direction.
* **metrics counters**: `.inc("name")` call sites vs the PREDEFINED
  list in `broker/metrics.py`, both directions.
* **alarms**: every `alarms.activate("name")` needs a matching
  `deactivate`/`is_active` somewhere (an alarm nothing ever clears is
  stuck forever) and vice versa (clearing an alarm nothing raises is
  dead code).  Module-level string constants are resolved.
* **tracepoints**: emitted => registered in KNOWN_KINDS (the old check
  #5) and registered => emitted from production code (dead
  registrations are events nobody can ever see), plus the retained.*
  ownership rule from check #7's sibling.
* **fault sites**: injected => registered in SITES (old check #6);
  registered-but-never-injected is reported as a warning.
* **span stages**: every stage the message-lifecycle span plane
  records (`spans.mark(ctx, "<stage>")` / `observe_stage("<stage>",
  dt)`) must be declared in `observe/spans.py` KNOWN_STAGES and every
  declared stage must be recorded somewhere — both directions error.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .index import ProjectIndex, _attr_chain
from .report import ERROR, WARN, Finding

CONFIG_PATH = os.path.join("emqx_tpu", "config", "config.py")
TRACEPOINTS_PATH = os.path.join("emqx_tpu", "observe", "tracepoints.py")
METRICS_PATH = os.path.join("emqx_tpu", "broker", "metrics.py")
SITES_PATH = os.path.join("emqx_tpu", "fault", "sites.py")
SPANS_PATH = os.path.join("emqx_tpu", "observe", "spans.py")

# retained.* tracepoints are owned by exactly these two modules (the
# retained device-index plane, ISSUE 7)
RETAINED_TP_FILES = (
    os.path.join("emqx_tpu", "models", "retained.py"),
    os.path.join("emqx_tpu", "broker", "retainer.py"),
)

FAULT_FNS = {"inject", "ainject", "peek", "mangle"}


# ------------------------------------------------------------ registries


def _module_dict_keys(idx: ProjectIndex, rel: str,
                      var: str) -> Optional[Set[str]]:
    """Top-level `VAR = {...}` string keys, statically."""
    fi = idx.files.get(rel)
    if fi is None or fi.tree is None:
        return None
    for node in ast.walk(fi.tree):
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if isinstance(tgt, ast.Name) and tgt.id == var and isinstance(
            node.value, ast.Dict
        ):
            return {
                k.value for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                )
            }
    return None


def known_tp_kinds(idx: ProjectIndex) -> Set[str]:
    return _module_dict_keys(idx, TRACEPOINTS_PATH, "KNOWN_KINDS") or set()


def known_fault_sites(idx: ProjectIndex) -> Set[str]:
    return _module_dict_keys(idx, SITES_PATH, "SITES") or set()


def known_span_stages(idx: ProjectIndex) -> Set[str]:
    return _module_dict_keys(idx, SPANS_PATH, "KNOWN_STAGES") or set()


def schema_keys(idx: ProjectIndex) -> Dict[str, Set[str]]:
    """SCHEMA as {namespace: {key, ...}} parsed statically."""
    fi = idx.files.get(CONFIG_PATH)
    out: Dict[str, Set[str]] = {}
    if fi is None or fi.tree is None:
        return out
    for node in ast.walk(fi.tree):
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if not (isinstance(tgt, ast.Name) and tgt.id == "SCHEMA"
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Dict):
                out[k.value] = {
                    f.value for f in v.keys
                    if isinstance(f, ast.Constant)
                    and isinstance(f.value, str)
                }
    return out


def predefined_metrics(idx: ProjectIndex) -> Set[str]:
    fi = idx.files.get(METRICS_PATH)
    if fi is None or fi.tree is None:
        return set()
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "PREDEFINED" and \
                isinstance(node.value, ast.List):
            return {
                el.value for el in node.value.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)
            }
    return set()


# ----------------------------------------------------------- collectors


def _literal_str(idx: ProjectIndex, module: str, node) -> Optional[str]:
    """A string literal or a module-level str constant by name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return idx.str_constants.get(f"{module}:{node.id}")
    return None


def collect_config_reads(
    idx: ProjectIndex, package_prefix: str = "emqx_tpu",
    extra_prefixes: Tuple[str, ...] = ("tools", "bench"),
):
    """Returns (key_reads, ns_dynamic, problems_input):

    * key_reads: {(ns, key): [(rel, line)]} — literal dotted reads plus
      subscript reads under a namespace-wide get;
    * ns_dynamic: namespaces read via f-strings/variables (dead-key
      direction treats every key of such a namespace as read).
    """
    schema = schema_keys(idx)
    key_reads: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    ns_dynamic: Set[str] = set()
    nonliteral: List[Tuple[str, int]] = []
    for rel, fi in idx.files.items():
        if fi.tree is None:
            continue
        mod = fi.module
        if not (mod.startswith(package_prefix)
                or mod.startswith(extra_prefixes)):
            continue
        # config.py itself: only channel_config_from & friends read
        # concrete keys; the generic schema machinery uses variables
        # and is invisible to the literal collector by construction
        # namespaces read wholesale in this file -> their keys seen as
        # plain string constants in the file count as key reads
        ns_whole: Set[str] = set()
        consts: Dict[str, List[int]] = {}
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                consts.setdefault(node.value, []).append(
                    getattr(node, "lineno", 0)
                )
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr in
                    ("get", "put")) or not node.args:
                continue
            arg = node.args[0]
            val = _literal_str(idx, mod, arg)
            if val is not None:
                ns, _, name = val.partition(".")
                if ns in schema and name:
                    if name in schema[ns]:
                        key_reads.setdefault((ns, name), []).append(
                            (rel, node.lineno)
                        )
                    else:
                        # undeclared read: recorded with key for the
                        # read=>declared direction
                        key_reads.setdefault((ns, name), []).append(
                            (rel, node.lineno)
                        )
                elif val in schema:
                    ns_whole.add(val)
            elif isinstance(arg, ast.JoinedStr):
                # f"ns.{...}" / f"{...}" — extract the static prefix
                prefix = ""
                if arg.values and isinstance(arg.values[0], ast.Constant):
                    prefix = str(arg.values[0].value)
                ns = prefix.split(".", 1)[0] if "." in prefix else None
                if ns in schema:
                    ns_dynamic.add(ns)
                else:
                    nonliteral.append((rel, node.lineno))
        for ns in ns_whole:
            for key in schema[ns]:
                # "ckpt.enable"-style nested keys are read as
                # "engine.ckpt.enable" dotted gets, not subscripts
                for part in {key, key.split(".")[-1]}:
                    if part in consts:
                        key_reads.setdefault((ns, key), []).append(
                            (rel, consts[part][0])
                        )
                        break
    return key_reads, ns_dynamic, nonliteral


def collect_tp_calls(idx: ProjectIndex,
                     package_prefix: str = "emqx_tpu"):
    """(rel, lineno, kind) for every literal-kind tp(...) call,
    including import aliases (`from ..tracepoints import tp as
    tracept`) and module-attribute calls (`_tps.tp(...)`)."""
    out = []
    for rel, fi in idx.files.items():
        if fi.tree is None or not fi.module.startswith(package_prefix):
            continue
        # local names bound to the tp entry point in this module
        aliases = {"tp"}
        for local, imp in idx.imports.get(fi.module, {}).items():
            if imp[0] == "symbol" and imp[2] == "tp" and \
                    imp[1].endswith("tracepoints"):
                aliases.add(local)
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            if name in aliases and node.args:
                kind = _literal_str(idx, fi.module, node.args[0])
                if kind is not None:
                    out.append((rel, node.lineno, kind))
    return out


def collect_fault_calls(idx: ProjectIndex,
                        package_prefix: str = "emqx_tpu"):
    """(rel, lineno, site|None) for fault.<fn>(...) calls outside the
    fault package itself (None = non-literal site)."""
    out = []
    for rel, fi in idx.files.items():
        if fi.tree is None or not fi.module.startswith(package_prefix):
            continue
        if fi.module.startswith("emqx_tpu.fault"):
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in FAULT_FNS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("fault", "_fault")
            ):
                continue
            site = (
                _literal_str(idx, fi.module, node.args[0])
                if node.args else None
            )
            out.append((rel, node.lineno, site))
    return out


def collect_span_marks(idx: ProjectIndex,
                       package_prefix: str = "emqx_tpu"):
    """(rel, lineno, stage|None) for every span-stage record point:
    `spans.mark(ctx, "<stage>")` / `_spans.mark(ctx, "<stage>")`
    anywhere in the package, plus the plane's own literal record points
    inside observe/spans.py (bare `mark(ctx, "<stage>")` and
    `observe_stage("<stage>", dt)` — the wire/forward stages close
    there).  A non-literal stage collects as None; spans.py's internal
    plumbing (the generic `observe_stage(stage, ...)` forward inside
    `mark`) is exempt from the literal requirement."""
    out = []
    for rel, fi in idx.files.items():
        if fi.tree is None or not fi.module.startswith(package_prefix):
            continue
        in_spans = rel == SPANS_PATH
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            if name == "mark":
                if isinstance(fn, ast.Attribute):
                    if not (isinstance(fn.value, ast.Name)
                            and fn.value.id in ("spans", "_spans")):
                        continue
                elif not in_spans:
                    continue  # unrelated bare mark() elsewhere
                if len(node.args) >= 2:
                    out.append((rel, node.lineno, _literal_str(
                        idx, fi.module, node.args[1]
                    )))
            elif name == "observe_stage" and node.args:
                stage = _literal_str(idx, fi.module, node.args[0])
                if stage is None and in_spans:
                    continue  # mark()'s generic forward, by design
                out.append((rel, node.lineno, stage))
    return out


def _collect_named_calls(idx: ProjectIndex, attrs: Set[str],
                         package_prefix: str = "emqx_tpu"):
    """(rel, lineno, attr, name) for `<x>.<attr>("<name>")` calls."""
    out = []
    for rel, fi in idx.files.items():
        if fi.tree is None or not fi.module.startswith(package_prefix):
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr in attrs):
                continue
            if not node.args:
                continue
            name = _literal_str(idx, fi.module, node.args[0])
            out.append((rel, node.lineno, fn.attr, name))
    return out


# --------------------------------------------------------------- checks


def check_config(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    schema = schema_keys(idx)
    if not schema:
        findings.append(Finding(
            code="cfg-schema", severity=ERROR, path=CONFIG_PATH, line=1,
            message="SCHEMA dict missing or unparseable", ident="SCHEMA",
        ))
        return findings
    key_reads, ns_dynamic, _nonlit = collect_config_reads(idx)
    # read => declared
    for (ns, key), sites in sorted(key_reads.items()):
        if key not in schema.get(ns, set()):
            rel, line = sites[0]
            findings.append(Finding(
                code="cfg-undeclared", severity=ERROR, path=rel,
                line=line,
                message=(
                    f"config key {ns}.{key!r} read but not declared in "
                    f"config/config.py SCHEMA[{ns!r}] — it always "
                    "resolves to the fallback"
                ),
                ident=f"{ns}.{key}",
            ))
    # declared => read
    for ns, keys in sorted(schema.items()):
        if ns in ns_dynamic:
            continue
        for key in sorted(keys):
            if (ns, key) not in key_reads:
                findings.append(Finding(
                    code="cfg-dead", severity=WARN, path=CONFIG_PATH,
                    line=1,
                    message=(
                        f"SCHEMA key {ns}.{key} is declared but never "
                        "read anywhere in emqx_tpu/tools/bench — "
                        "silent no-op config; wire it or remove it"
                    ),
                    ident=f"{ns}.{key}",
                ))
    return findings


def check_tracepoints(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    known = known_tp_kinds(idx)
    if not known:
        findings.append(Finding(
            code="tp-registry", severity=ERROR, path=TRACEPOINTS_PATH,
            line=1, message="KNOWN_KINDS registry missing",
            ident="KNOWN_KINDS",
        ))
        return findings
    calls = collect_tp_calls(idx)
    emitted: Dict[str, List[Tuple[str, int]]] = {}
    for rel, line, kind in calls:
        emitted.setdefault(kind, []).append((rel, line))
        if kind not in known:
            findings.append(Finding(
                code="tp-unregistered", severity=ERROR, path=rel,
                line=line,
                message=(
                    f"tp kind {kind!r} not registered in "
                    "observe/tracepoints.py KNOWN_KINDS"
                ),
                ident=kind,
            ))
        if kind.startswith("retained.") and rel not in RETAINED_TP_FILES:
            findings.append(Finding(
                code="tp-owner", severity=ERROR, path=rel, line=line,
                message=(
                    f"retained.* tracepoint {kind!r} emitted outside "
                    "models/retained.py / broker/retainer.py"
                ),
                ident=kind,
            ))
    for kind in sorted(known - set(emitted)):
        findings.append(Finding(
            code="tp-dead", severity=ERROR, path=TRACEPOINTS_PATH,
            line=1,
            message=(
                f"registered tracepoint kind {kind!r} is never emitted "
                "from production code — remove the registration or "
                "emit it"
            ),
            ident=kind,
        ))
    return findings


def check_fault_sites(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    calls = collect_fault_calls(idx)
    known = known_fault_sites(idx)
    if calls and not known:
        findings.append(Finding(
            code="fault-registry", severity=ERROR, path=SITES_PATH,
            line=1, message="SITES registry missing", ident="SITES",
        ))
        return findings
    used: Set[str] = set()
    for rel, line, site in calls:
        if site is None:
            findings.append(Finding(
                code="fault-nonliteral", severity=ERROR, path=rel,
                line=line,
                message=(
                    "fault call with a non-literal site (the registry "
                    "lint needs a string literal)"
                ),
                ident=f"{rel}:nonliteral",
            ))
            continue
        used.add(site)
        if site not in known:
            findings.append(Finding(
                code="fault-unregistered", severity=ERROR, path=rel,
                line=line,
                message=(
                    f"fault site {site!r} not registered in "
                    "emqx_tpu/fault/sites.py SITES"
                ),
                ident=site,
            ))
    for site in sorted(known - used):
        findings.append(Finding(
            code="fault-dead", severity=WARN, path=SITES_PATH, line=1,
            message=(
                f"fault site {site!r} is registered but never injected "
                "from production code"
            ),
            ident=site,
        ))
    return findings


def check_span_stages(idx: ProjectIndex) -> List[Finding]:
    """Span-stage registry, both directions (the tracepoint/fault-site
    contract): every stage recorded by the span plane must be declared
    in observe/spans.py KNOWN_STAGES, and every declared stage must be
    recorded somewhere — a dead stage is a latency column dashboards
    key on that can never fill."""
    findings: List[Finding] = []
    marks = collect_span_marks(idx)
    known = known_span_stages(idx)
    if marks and not known:
        findings.append(Finding(
            code="span-registry", severity=ERROR, path=SPANS_PATH,
            line=1, message="KNOWN_STAGES registry missing",
            ident="KNOWN_STAGES",
        ))
        return findings
    used: Set[str] = set()
    for rel, line, stage in marks:
        if stage is None:
            findings.append(Finding(
                code="span-nonliteral", severity=ERROR, path=rel,
                line=line,
                message=(
                    "span stage record with a non-literal stage name "
                    "(the registry lint needs a string literal)"
                ),
                ident=f"{rel}:nonliteral",
            ))
            continue
        used.add(stage)
        if stage not in known:
            findings.append(Finding(
                code="span-unregistered", severity=ERROR, path=rel,
                line=line,
                message=(
                    f"span stage {stage!r} not declared in "
                    "observe/spans.py KNOWN_STAGES"
                ),
                ident=stage,
            ))
    for stage in sorted(known - used):
        findings.append(Finding(
            code="span-dead", severity=ERROR, path=SPANS_PATH, line=1,
            message=(
                f"span stage {stage!r} is declared but never recorded "
                "by any production code path — remove the declaration "
                "or record it"
            ),
            ident=stage,
        ))
    return findings


def check_metrics(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    declared = predefined_metrics(idx)
    if not declared:
        findings.append(Finding(
            code="metric-registry", severity=ERROR, path=METRICS_PATH,
            line=1, message="PREDEFINED counter list missing",
            ident="PREDEFINED",
        ))
        return findings
    incs = _collect_named_calls(idx, {"inc"})
    used: Set[str] = set()
    dynamic = False
    for rel, line, _attr, name in incs:
        if rel == METRICS_PATH:
            continue
        if name is None:
            dynamic = True
            continue
        used.add(name)
        if name not in declared:
            findings.append(Finding(
                code="metric-undeclared", severity=WARN, path=rel,
                line=line,
                message=(
                    f"counter {name!r} incremented but not in "
                    "broker/metrics.py PREDEFINED — it is invisible "
                    "until first inc and unorderable in exports"
                ),
                ident=name,
            ))
    if not dynamic:
        for name in sorted(declared - used):
            findings.append(Finding(
                code="metric-dead", severity=WARN, path=METRICS_PATH,
                line=1,
                message=(
                    f"PREDEFINED counter {name!r} is never incremented "
                    "by any production code path"
                ),
                ident=name,
            ))
    return findings


def check_alarms(idx: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    calls = _collect_named_calls(
        idx, {"activate", "deactivate", "is_active"}
    )
    activated: Dict[str, Tuple[str, int]] = {}
    cleared: Dict[str, Tuple[str, int]] = {}
    for rel, line, attr, name in calls:
        if name is None or rel.startswith(
            os.path.join("emqx_tpu", "observe")
        ):
            continue  # the AlarmManager itself + observe plumbing
        if attr == "activate":
            activated.setdefault(name, (rel, line))
        else:
            cleared.setdefault(name, (rel, line))
    for name, (rel, line) in sorted(activated.items()):
        if name not in cleared:
            findings.append(Finding(
                code="alarm-stuck", severity=WARN, path=rel, line=line,
                message=(
                    f"alarm {name!r} is activated but no code path "
                    "ever deactivates or polls it — once raised it is "
                    "stuck forever"
                ),
                ident=name,
            ))
    for name, (rel, line) in sorted(cleared.items()):
        if name not in activated:
            findings.append(Finding(
                code="alarm-dead", severity=WARN, path=rel, line=line,
                message=(
                    f"alarm {name!r} is deactivated/polled but never "
                    "activated anywhere — dead lifecycle code"
                ),
                ident=name,
            ))
    return findings


def check_registries(idx: ProjectIndex) -> List[Finding]:
    out: List[Finding] = []
    out.extend(check_config(idx))
    out.extend(check_tracepoints(idx))
    out.extend(check_fault_sites(idx))
    out.extend(check_span_stages(idx))
    out.extend(check_metrics(idx))
    out.extend(check_alarms(idx))
    return out
