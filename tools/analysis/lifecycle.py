"""Pass (f): task & resource lifecycle.

`asyncio.create_task` keeps only a *weak* reference to the task it
returns: a task nobody retains can be garbage-collected mid-flight, and
a task nobody awaits silently eats every exception it raises.  The
34 create_task/executor sites in this tree are the broker's background
organs — heartbeats, sweepers, delivery shards, resync pumps — and a
dropped or leaked one is a silent outage.  This pass enforces the
lifecycle contract end to end:

* **retention** (``task-unretained``, error): the result of every
  ``create_task``/``ensure_future`` must go somewhere — a name, a
  ``self.<attr>``, a container (``.append``/``.add``/dict slot), a
  registry call argument (the `DeliveryPool` shape), an ``await`` or a
  ``return``.  A bare expression statement is fire-and-forget: the GC
  may drop it and its exception is never observed.  Deliberate
  detachment needs ``# analysis: detached-task(<why>)``.
* **cancellation reach** (``task-leak``, error): a task retained in
  ``self.<attr>`` (scalar, list/set, or dict slot) must have a cancel/
  join path *somewhere in its class* — ``self.<attr>.cancel()``, a
  ``.cancel()``/``await`` on a local or loop-target traced from the
  attribute, or ``gather(*self.<attr>)``.  A task that is stored but
  never cancelled outlives (and silently outlasts) every shutdown.
* **teardown reach** (``task-cancel-unreachable``, warn): the cancel
  site must be reachable (over the call graph) from a teardown-shaped
  entry point (``close``/``stop``/``shutdown``/``__aexit__``/...);
  a cancel only a request handler can reach still leaks on shutdown.
* **resources** (``resource-leak``, error): ``self.<attr>`` bound from
  ``open()``/``socket.socket()``/``ThreadPoolExecutor()`` must reach a
  ``close``/``shutdown`` in its class; a *local* so bound must be
  closed in-function, returned, stored, or passed on — `with` blocks
  satisfy this by construction.
* **callback pairing** (``hook-unpaired`` / ``slot-unpaired``, error):
  a class with a teardown method that registers a hook callback
  (``hooks.put(point, self.cb)``) must also ``hooks.delete`` that
  point; one that assigns a single-slot callback on a foreign object
  (``other.on_change = self._cb``) must clear it (``= None``).
  Registrations that genuinely live for the whole process carry
  ``# analysis: lifetime=node(<why>)``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .index import CALL, FuncInfo, ProjectIndex, _attr_chain, \
    _walk_own_body
from .report import ERROR, WARN, Finding

_SPAWN = {"create_task", "ensure_future"}
_TEARDOWN_RE = re.compile(
    r"(close|stop|shutdown|teardown|unload|uninstall|disable|abort"
    r"|cancel|__aexit__|__exit__|leave)", re.I,
)
_RESOURCE_CTORS = {
    "open": ("file", ("close",)),
    "socket": ("socket", ("close",)),
    "create_connection": ("socket", ("close",)),
    "ThreadPoolExecutor": ("executor", ("shutdown",)),
    "ProcessPoolExecutor": ("executor", ("shutdown",)),
}
_CLOSE_VERBS = {"close", "shutdown", "aclose"}
_CONTAINER_ADD = {"append", "add", "put", "put_nowait", "insert"}


@dataclass
class _TaskAttr:
    cls: str
    attr: str
    path: str
    line: int
    qual: str  # method that stores it


@dataclass
class _Stats:
    spawn_sites: int = 0
    retained_attrs: int = 0
    resources: int = 0
    hook_puts: int = 0

    def to_dict(self) -> Dict[str, int]:
        return {
            "spawn_sites": self.spawn_sites,
            "retained_task_attrs": self.retained_attrs,
            "resource_attrs": self.resources,
            "hook_registrations": self.hook_puts,
        }


def check_lifecycle(
    idx: ProjectIndex,
    package_prefix: str = "emqx_tpu",
) -> Tuple[List[Finding], Dict[str, int]]:
    st = _Stats()
    findings: List[Finding] = []
    findings += _check_retention(idx, package_prefix, st)
    findings += _check_task_attrs(idx, package_prefix, st)
    findings += _check_resources(idx, package_prefix, st)
    findings += _check_callbacks(idx, package_prefix, st)
    return findings, st.to_dict()


# ---------------------------------------------------------------- retention


def _is_spawn(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        # covers asyncio.create_task, loop.create_task AND call-chain
        # receivers like asyncio.get_running_loop().create_task(...)
        return f.attr in _SPAWN
    return isinstance(f, ast.Name) and f.id in _SPAWN


def _check_retention(idx: ProjectIndex, prefix: str,
                     st: _Stats) -> List[Finding]:
    findings: List[Finding] = []
    for key, info in idx.funcs.items():
        if not info.module.startswith(prefix):
            continue
        fi = idx.files[info.path]
        # own-body walks: nested defs are their own FuncInfos and must
        # not be visited twice
        for node in _walk_own_body(info.node):
            if not _is_spawn(node):
                continue
            st.spawn_sites += 1
        for node in _walk_own_body(info.node):
            # fire-and-forget = an Expr statement whose value IS the
            # spawn call; every other position (assignment, container
            # add, argument, await, return, comprehension) retains it
            if not (isinstance(node, ast.Expr)
                    and _is_spawn(node.value)):
                continue
            lineno = node.value.lineno
            if lineno in fi.ignored_lines:
                continue
            ann = fi.annotations.get(lineno, "")
            if ann.startswith("detached-task"):
                reason = ann[len("detached-task"):].strip("(): ")
                if reason:
                    continue
                findings.append(Finding(
                    code="task-annotation", severity=ERROR,
                    path=info.path, line=lineno,
                    message=(
                        "detached-task annotation without a reason "
                        "(write `# analysis: detached-task(<why>)`)"
                    ),
                    ident=f"{info.qualname}:L-ann",
                ))
                continue
            target = _spawn_target(node.value)
            findings.append(Finding(
                code="task-unretained", severity=ERROR,
                path=info.path, line=lineno,
                message=(
                    f"{info.qualname} fires {target} and drops the "
                    "Task: asyncio holds only a weak reference (the GC "
                    "can collect it mid-flight) and its exception is "
                    "never observed — retain it (attr/set/registry) "
                    "and cancel it on shutdown, or annotate "
                    "`# analysis: detached-task(<why>)`"
                ),
                ident=f"{info.qualname}:{target}",
            ))
    return findings


def _spawn_target(call: ast.Call) -> str:
    """Human name of the coroutine being spawned."""
    if call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            chain = _attr_chain(inner.func)
            if chain:
                return f"create_task({'.'.join(chain)}(...))"
    return "create_task(...)"


# ----------------------------------------------------------- cancel reach


def _check_task_attrs(idx: ProjectIndex, prefix: str,
                      st: _Stats) -> List[Finding]:
    findings: List[Finding] = []
    teardown_reach = _teardown_reachable(idx)
    for cls_list in idx.classes.values():
        for ci in cls_list:
            if not ci.module.startswith(prefix):
                continue
            stored: Dict[str, _TaskAttr] = {}
            for m in ci.methods.values():
                for attr, line in _task_stores(m):
                    stored.setdefault(attr, _TaskAttr(
                        ci.name, attr, ci.path, line, m.qualname))
            if not stored:
                continue
            st.retained_attrs += len(stored)
            cancelled: Dict[str, List[FuncInfo]] = {}
            for m in ci.methods.values():
                for attr in _cancel_evidence(m, set(stored)):
                    cancelled.setdefault(attr, []).append(m)
            fi = idx.files[ci.path]
            for attr, ta in sorted(stored.items()):
                if ta.line in fi.ignored_lines:
                    continue
                if fi.annotations.get(ta.line, "").startswith(
                        "detached-task"):
                    continue
                ev = cancelled.get(attr)
                if not ev:
                    findings.append(Finding(
                        code="task-leak", severity=ERROR, path=ci.path,
                        line=ta.line,
                        message=(
                            f"{ci.name}.{attr} retains asyncio task(s) "
                            "but no method of the class cancels or "
                            "awaits them — the task outlives every "
                            "shutdown (add a cancel/join on the "
                            "close/stop path)"
                        ),
                        ident=f"{ci.name}.{attr}",
                    ))
                    continue
                if not any(m.key in teardown_reach for m in ev):
                    findings.append(Finding(
                        code="task-cancel-unreachable", severity=WARN,
                        path=ci.path, line=ta.line,
                        message=(
                            f"{ci.name}.{attr} is cancelled only in "
                            f"{', '.join(m.qualname for m in ev)}, "
                            "which no close/stop/shutdown-shaped "
                            "method reaches — shutdown leaks the task"
                        ),
                        ident=f"{ci.name}.{attr}:reach",
                    ))
    return findings


def _task_stores(m: FuncInfo):
    """(attr, line) pairs where a spawn result lands in self.<attr> —
    scalar assign, dict slot, or container .append/.add."""
    for node in ast.walk(m.node):
        if isinstance(node, ast.Assign) and _is_spawn(node.value):
            for t in node.targets:
                attr = _self_attr_of(t)
                if attr:
                    yield attr, node.lineno
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.ListComp) and _is_spawn(
                node.value.elt):
            for t in node.targets:
                attr = _self_attr_of(t)
                if attr:
                    yield attr, node.lineno
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if (chain and len(chain) == 3 and chain[0] == "self"
                    and chain[-1] in _CONTAINER_ADD
                    and any(_is_spawn(a) for a in node.args)):
                yield chain[1], node.lineno


def _self_attr_of(t) -> Optional[str]:
    """self.<attr> or self.<attr>[k] assignment target -> attr."""
    if isinstance(t, ast.Subscript):
        t = t.value
    chain = _attr_chain(t)
    if chain and chain[0] == "self" and len(chain) == 2:
        return chain[1]
    return None


def _cancel_evidence(m: FuncInfo, attrs: Set[str]) -> Set[str]:
    """Attrs (from `attrs`) this method cancels, awaits or gathers —
    directly (`self.t.cancel()`), through a local alias, or through a
    for-target iterating the attr (incl. `.values()`/`list(...)`)."""
    out: Set[str] = set()
    derived = _derived_names(m, attrs)
    for node in ast.walk(m.node):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if not chain:
                continue
            if chain[-1] == "cancel":
                recv = chain[:-1]
                if recv[0] == "self" and len(recv) == 2 \
                        and recv[1] in attrs:
                    out.add(recv[1])
                elif len(recv) == 1 and recv[0] in derived:
                    out |= derived[recv[0]]
            elif chain[-1] == "gather":
                out |= _attrs_mentioned(node, attrs)
        elif isinstance(node, ast.Await):
            chain = _attr_chain(node.value)
            if chain and chain[0] == "self" and len(chain) == 2 \
                    and chain[1] in attrs:
                out.add(chain[1])
            elif chain and len(chain) == 1 and chain[0] in derived:
                out |= derived[chain[0]]
    return out


def _derived_names(m: FuncInfo, attrs: Set[str]) -> Dict[str, Set[str]]:
    """Local names whose value derives from self.<attr>: `t = self.x`,
    `for t in self.tasks` / `.values()` / `list(self.tasks) + [...]` —
    a name derived from several attrs carries all of them."""
    derived: Dict[str, Set[str]] = {}

    def sources(value) -> Set[str]:
        src = _attrs_mentioned(value, attrs)
        for n in ast.walk(value):
            if isinstance(n, ast.Name) and n.id in derived:
                src |= derived[n.id]
        return src

    for _ in range(2):  # one extra round for alias-of-alias chains
        for node in ast.walk(m.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                if len(targets) == 1 and isinstance(
                        targets[0], ast.Name):
                    src = sources(node.value)
                    if src:
                        derived.setdefault(
                            targets[0].id, set()).update(src)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                src = sources(node.iter)
                if src and isinstance(node.target, ast.Name):
                    derived.setdefault(
                        node.target.id, set()).update(src)
    return derived


def _attrs_mentioned(node, attrs: Set[str]) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name) and n.value.id == "self" \
                and n.attr in attrs:
            out.add(n.attr)
    return out


def _teardown_reachable(idx: ProjectIndex) -> Set[str]:
    """Function keys reachable over CALL edges from any teardown-shaped
    function (by name)."""
    roots = {
        key for key, info in idx.funcs.items()
        if _TEARDOWN_RE.search(info.qualname.split(".")[-1])
    }
    out_edges: Dict[str, List[str]] = {}
    for e in idx.edges:
        if e.kind == CALL:
            out_edges.setdefault(e.caller, []).append(e.callee)
    seen = set(roots)
    queue = list(roots)
    while queue:
        cur = queue.pop()
        for nxt in out_edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                queue.append(nxt)
    return seen


# ------------------------------------------------------------- resources


def _resource_ctor(node) -> Optional[Tuple[str, Tuple[str, ...]]]:
    if not isinstance(node, ast.Call):
        return None
    chain = _attr_chain(node.func)
    if not chain:
        return None
    got = _RESOURCE_CTORS.get(chain[-1])
    if got is None:
        return None
    if chain[-1] == "socket" and len(chain) == 1:
        return None  # bare socket() is ambiguous; socket.socket() isn't
    return got


def _check_resources(idx: ProjectIndex, prefix: str,
                     st: _Stats) -> List[Finding]:
    findings: List[Finding] = []
    for cls_list in idx.classes.values():
        for ci in cls_list:
            if not ci.module.startswith(prefix):
                continue
            held: Dict[str, Tuple[str, int, str]] = {}
            for m in ci.methods.values():
                for node in ast.walk(m.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    res = _resource_ctor(node.value)
                    if res is None:
                        continue
                    for t in node.targets:
                        attr = _self_attr_of(t)
                        if attr:
                            held[attr] = (res[0], node.lineno,
                                          m.qualname)
            if not held:
                continue
            st.resources += len(held)
            closed: Set[str] = set()
            for m in ci.methods.values():
                derived = _derived_names(m, set(held))
                for node in ast.walk(m.node):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = _attr_chain(node.func)
                    if not chain or chain[-1] not in _CLOSE_VERBS:
                        continue
                    if len(chain) == 3 and chain[0] == "self":
                        closed.add(chain[1])
                    elif len(chain) == 2 and chain[0] in derived:
                        # f = self._files.pop(k); f.close()
                        closed |= derived[chain[0]]
            fi = idx.files[ci.path]
            for attr, (kind, line, qual) in sorted(held.items()):
                if attr in closed or line in fi.ignored_lines:
                    continue
                findings.append(Finding(
                    code="resource-leak", severity=ERROR, path=ci.path,
                    line=line,
                    message=(
                        f"{ci.name}.{attr} holds a {kind} opened in "
                        f"{qual} but no method of the class closes it "
                        "— add a close()/shutdown() on the teardown "
                        "path"
                    ),
                    ident=f"{ci.name}.{attr}",
                ))
    # function-local resources: opened, never closed, never escapes
    for key, info in idx.funcs.items():
        if not info.module.startswith(prefix):
            continue
        fi = idx.files[info.path]
        findings.extend(_check_local_resources(info, fi))
    return findings


def _check_local_resources(info: FuncInfo, fi) -> List[Finding]:
    findings: List[Finding] = []
    opened: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(info.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            res = _resource_ctor(node.value)
            if res is not None:
                opened[node.targets[0].id] = (res[0], node.lineno)
    if not opened:
        return findings
    for node in ast.walk(info.node):
        # any escape or close clears the name: with-context, close(),
        # return, attr store, container add, call argument
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and len(chain) == 2 and chain[1] in _CLOSE_VERBS:
                opened.pop(chain[0], None)
            for a in list(node.args) + [kw.value for kw in
                                        node.keywords]:
                if isinstance(a, ast.Name):
                    opened.pop(a.id, None)
        elif isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name):
            opened.pop(node.value.id, None)
        elif isinstance(node, ast.Assign):
            # aliasing or storing the handle hands ownership off:
            # `self._f = f`, `x = f`, `pair = (f, g)` all escape
            if not _resource_ctor(node.value):
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name):
                        opened.pop(n.id, None)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Name):
                    opened.pop(item.context_expr.id, None)
    for name, (kind, line) in sorted(opened.items()):
        if line in fi.ignored_lines:
            continue
        findings.append(Finding(
            code="resource-leak", severity=ERROR, path=info.path,
            line=line,
            message=(
                f"{info.qualname} opens {kind} {name!r} and neither "
                "closes it nor hands it off — use a `with` block or "
                "close it on every path"
            ),
            ident=f"{info.qualname}:{name}",
        ))
    return findings


# ---------------------------------------------------- callback pairing


def _check_callbacks(idx: ProjectIndex, prefix: str,
                     st: _Stats) -> List[Finding]:
    findings: List[Finding] = []
    for cls_list in idx.classes.values():
        for ci in cls_list:
            if not ci.module.startswith(prefix):
                continue
            has_teardown = any(
                _TEARDOWN_RE.search(name) for name in ci.methods
            )
            if not has_teardown:
                continue  # process-lifetime singleton: nothing to
                # reach the unregister from
            fi = idx.files[ci.path]
            puts: List[Tuple[str, int, str]] = []  # (point, line, qual)
            deletes: Set[str] = set()
            slot_sets: List[Tuple[str, str, int, str]] = []
            slot_clears: Set[Tuple[str, str]] = set()
            for m in ci.methods.values():
                for node in ast.walk(m.node):
                    if isinstance(node, ast.Call):
                        chain = _attr_chain(node.func)
                        if not chain or len(chain) < 2:
                            continue
                        recv_is_hooks = chain[-2] == "h" or any(
                            "hook" in c.lower() for c in chain[:-1]
                        )
                        if not recv_is_hooks:
                            continue
                        point = _str_arg(node, 0)
                        if chain[-1] == "put" and point and \
                                _is_self_bound(node, 1):
                            puts.append((point, node.lineno,
                                         m.qualname))
                        elif chain[-1] == "delete" and point:
                            deletes.add(point)
                    elif isinstance(node, ast.Assign):
                        got = _slot_assign(node)
                        if got is None:
                            continue
                        recv, slot, cleared = got
                        if cleared:
                            slot_clears.add((recv, slot))
                        elif _is_self_bound_value(node.value):
                            slot_sets.append((recv, slot,
                                              node.lineno, m.qualname))
            st.hook_puts += len(puts)
            for point, line, qual in puts:
                if point in deletes or line in fi.ignored_lines:
                    continue
                if fi.annotations.get(line, "").startswith("lifetime="):
                    continue
                findings.append(Finding(
                    code="hook-unpaired", severity=ERROR, path=ci.path,
                    line=line,
                    message=(
                        f"{ci.name}.{qual.split('.')[-1]} registers a "
                        f"callback on hook point {point!r} but the "
                        "class (which has a teardown method) never "
                        "hooks.delete()s it — a stopped instance keeps "
                        "receiving events; delete it on teardown or "
                        "annotate `# analysis: lifetime=node(<why>)`"
                    ),
                    ident=f"{ci.name}:{point}",
                ))
            owned = _owned_attrs(ci)
            for recv, slot, line, qual in slot_sets:
                if (recv, slot) in slot_clears \
                        or line in fi.ignored_lines:
                    continue
                if fi.annotations.get(line, "").startswith("lifetime="):
                    continue
                root = recv.split(".")[1] if recv.startswith("self.") \
                    else recv
                if root in owned:
                    continue  # the holder dies with us; no dangle
                findings.append(Finding(
                    code="slot-unpaired", severity=ERROR, path=ci.path,
                    line=line,
                    message=(
                        f"{ci.name}.{qual.split('.')[-1]} installs a "
                        f"bound callback into {recv}.{slot} (an object "
                        "it does not own) and never clears it — the "
                        "slot keeps this instance alive and firing "
                        f"after teardown; set {recv}.{slot} = None on "
                        "close or annotate "
                        "`# analysis: lifetime=node(<why>)`"
                    ),
                    ident=f"{ci.name}:{recv}.{slot}",
                ))
    return findings


def _str_arg(node: ast.Call, i: int) -> Optional[str]:
    if len(node.args) > i and isinstance(node.args[i], ast.Constant) \
            and isinstance(node.args[i].value, str):
        return node.args[i].value
    return None


def _is_self_bound(node: ast.Call, i: int) -> bool:
    """Arg i references self (bound method, self itself, or a lambda
    closing over self) — i.e. registering keeps THIS instance alive."""
    if len(node.args) <= i:
        return False
    return _is_self_bound_value(node.args[i])


def _is_self_bound_value(v) -> bool:
    for n in ast.walk(v):
        if isinstance(n, ast.Name) and n.id == "self":
            return True
    return False


def _slot_assign(node: ast.Assign):
    """`<recv>.on_<slot> = <value>` -> (recv_text, slot, cleared)."""
    if len(node.targets) != 1:
        return None
    t = node.targets[0]
    if not isinstance(t, ast.Attribute) or not t.attr.startswith("on_"):
        return None
    chain = _attr_chain(t)
    if not chain or len(chain) < 3:
        return None  # self.on_x = ... assigns OUR slot, not a foreign one
    recv = ".".join(chain[:-1])
    cleared = isinstance(node.value, ast.Constant) \
        and node.value.value is None
    return recv, t.attr, cleared


def _owned_attrs(ci) -> Set[str]:
    """Attrs assigned from a constructor call in __init__ — objects
    this class created and therefore owns."""
    out: Set[str] = set()
    init = ci.methods.get("__init__")
    if init is None:
        return out
    for node in ast.walk(init.node):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            for t in node.targets:
                chain = _attr_chain(t)
                if chain and chain[0] == "self" and len(chain) == 2:
                    out.add(chain[1])
    return out
