"""Whole-project AST index + call graph — the shared substrate.

Every pass used to re-walk the tree (eight `os.walk` + `ast.parse`
sweeps in the old `tools/check.py`); here the project is parsed ONCE
into a `ProjectIndex`:

* per file: source, AST, `# check: ignore` / `# analysis:` annotated
  lines, module name;
* per module: imports (relative imports resolved against the package),
  top-level defs, classes with base links;
* per function: a `FuncInfo` keyed `module:Qual.name`, including nested
  defs;
* a call graph with typed edges: plain calls, `asyncio.create_task`
  targets, executor hops (`asyncio.to_thread`, `run_in_executor`,
  `threading.Thread(target=...)`, concurrent-futures submits).

Receiver resolution is dialyzer-grade best-effort, not sound:

* `self.m()` resolves through the enclosing class and its project base
  classes;
* `x.m()` resolves when `x` is a local bound from a project-class
  constructor, a parameter whose type was inferred from call sites, or
  a `self.attr` whose type was inferred the same way (including
  list-of-T from list comprehensions of constructors, probed through
  `x[i].m()`);
* as a last resort a method name defined by exactly ONE project class
  (and not a generic container verb) resolves by uniqueness.

Attribute/parameter types reach a fixed point over a few rounds: a
constructor call with typed arguments types the callee's parameters,
which type the `self.x = param` attributes, which type the next round's
receivers.  Unresolvable calls simply produce no edge — passes treat
missing edges as "unknown", never as "safe".
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# call-edge kinds
CALL = "call"  # same-thread call: roles propagate caller -> callee
EXECUTOR = "executor"  # to_thread / run_in_executor / Thread: worker hop
TASK = "task"  # create_task / ensure_future: stays on the loop

# method names too generic for unique-name fallback resolution (they
# collide with dict/list/file/asyncio verbs on untyped receivers)
_GENERIC_METHODS = {
    "get", "put", "set", "add", "remove", "close", "start", "stop",
    "send", "recv", "write", "read", "flush", "append", "pop", "insert",
    "clear", "update", "keys", "values", "items", "join", "wait",
    "acquire", "release", "submit", "match", "delete", "encode",
    "decode", "count", "copy", "index", "extend", "sort", "split",
    "strip", "load", "save", "tick", "run", "call", "cancel", "result",
    "done", "open", "name", "next", "drain", "reset", "stats", "check",
    "setdefault", "discard", "find", "all", "format", "replace", "info",
    "warning", "error", "debug", "exception", "lower", "upper",
}


@dataclass
class FileInfo:
    path: str  # absolute
    rel: str  # repo-relative
    module: str  # dotted ("emqx_tpu.broker.broker", "tools.ckpt_dump")
    src: str
    tree: Optional[ast.AST]
    syntax_error: Optional[Tuple[int, str]] = None
    ignored_lines: Set[int] = field(default_factory=set)
    # lineno -> annotation text after "# analysis:" (stripped)
    annotations: Dict[int, str] = field(default_factory=dict)
    lines: List[str] = field(default_factory=list)


@dataclass
class FuncInfo:
    key: str  # "module:Qual.name"
    module: str
    qualname: str  # "Class.method" | "fn" | "fn.inner"
    path: str  # repo-relative
    lineno: int
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    cls: Optional[str] = None  # enclosing class name, if a method


@dataclass
class ClassInfo:
    name: str
    module: str
    path: str
    lineno: int
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # raw base names
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    # attr -> set of project class names (inferred)
    attr_types: Dict[str, Set[str]] = field(default_factory=dict)
    # attr -> set of project class names for list-of-T attributes
    attr_elem_types: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class Edge:
    caller: str  # FuncInfo.key
    callee: str  # FuncInfo.key
    kind: str  # CALL | EXECUTOR | TASK
    lineno: int


def _is_def(n) -> bool:
    return isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))


def _attr_chain(node) -> Optional[List[str]]:
    """Attribute/Name chain as a list, e.g. self.ds.flush_all ->
    ["self", "ds", "flush_all"]; None for non-trivial receivers."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _unwrap_callable(node):
    """Peel functools.partial(f, ...) down to f; pass through lambdas
    and plain callable references."""
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial" and node.args:
            return _unwrap_callable(node.args[0])
    return node


class ProjectIndex:
    def __init__(self, repo: str):
        self.repo = repo
        self.files: Dict[str, FileInfo] = {}  # rel -> FileInfo
        self.modules: Dict[str, FileInfo] = {}  # dotted -> FileInfo
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}  # name -> defs
        self.class_by_qual: Dict[str, ClassInfo] = {}  # "mod:Cls"
        self.edges: List[Edge] = []
        # module -> {local name -> ("module", dotted) | ("symbol",
        # dotted_module, symbol)}
        self.imports: Dict[str, Dict[str, tuple]] = {}
        # method name -> [FuncInfo] across all project classes
        self.method_index: Dict[str, List[FuncInfo]] = {}
        # module-level str constants: "module:NAME" -> value
        self.str_constants: Dict[str, str] = {}
        # executor-hop target keys (for role roots)
        self.executor_targets: Set[str] = set()

    # ----------------------------------------------------------- loading

    @classmethod
    def build(cls, repo: str, targets: List[str]) -> "ProjectIndex":
        idx = cls(repo)
        for rel in _iter_py(repo, targets):
            idx._load_file(rel)
        idx._index_defs()
        idx._infer_types()
        idx._build_edges()
        return idx

    def _load_file(self, rel: str) -> None:
        path = os.path.join(self.repo, rel)
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        module = rel[:-3].replace(os.sep, ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        lines = src.splitlines()
        ignored = set()
        annotations = {}
        for i, line in enumerate(lines):
            if "# check: ignore" in line:
                ignored.add(i + 1)
            if "# analysis:" in line:
                annotations[i + 1] = line.split("# analysis:", 1)[1].strip()
        try:
            tree = ast.parse(src, path)
            err = None
        except SyntaxError as e:
            tree, err = None, (e.lineno or 0, e.msg or "syntax error")
        fi = FileInfo(
            path=path, rel=rel, module=module, src=src, tree=tree,
            syntax_error=err, ignored_lines=ignored,
            annotations=annotations, lines=lines,
        )
        self.files[rel] = fi
        self.modules[module] = fi

    # ---------------------------------------------------------- indexing

    def _index_defs(self) -> None:
        for fi in self.files.values():
            if fi.tree is None:
                continue
            self.imports[fi.module] = self._collect_imports(fi)
            self._collect_constants(fi)
            self._walk_scope(fi, fi.tree.body, prefix="", cls=None)

    def _collect_imports(self, fi: FileInfo) -> Dict[str, tuple]:
        out: Dict[str, tuple] = {}
        pkg = fi.module.rsplit(".", 1)[0] if "." in fi.module else ""
        is_pkg = fi.rel.endswith("__init__.py")
        if is_pkg:
            pkg = fi.module
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        "module", a.name if a.asname else
                        a.name.split(".")[0],
                    )
                    if a.asname:
                        out[a.asname] = ("module", a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                if node.level:
                    # level 1 = this module's package, 2 = parent, ...
                    parts = (fi.module if is_pkg else (
                        fi.module.rsplit(".", 1)[0]
                        if "." in fi.module else ""
                    )).split(".")
                    if node.level - 1 > 0:
                        parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(p for p in parts if p)
                    target = (
                        f"{base}.{node.module}" if node.module else base
                    )
                else:
                    target = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    sub = f"{target}.{a.name}"
                    if sub in self.modules:
                        out[local] = ("module", sub)
                    else:
                        out[local] = ("symbol", target, a.name)
        return out

    def _collect_constants(self, fi: FileInfo) -> None:
        for node in fi.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.str_constants[
                    f"{fi.module}:{node.targets[0].id}"
                ] = node.value.value

    def _walk_scope(self, fi: FileInfo, body, prefix: str,
                    cls: Optional[ClassInfo]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(
                    name=node.name, module=fi.module, path=fi.rel,
                    lineno=node.lineno, node=node,
                    bases=[
                        b for b in (
                            (_attr_chain(base) or [None])[-1]
                            for base in node.bases
                        ) if b
                    ],
                )
                self.classes.setdefault(node.name, []).append(ci)
                self.class_by_qual[f"{fi.module}:{node.name}"] = ci
                self._walk_scope(fi, node.body, prefix=node.name, cls=ci)
            elif _is_def(node):
                qual = f"{prefix}.{node.name}" if prefix else node.name
                info = FuncInfo(
                    key=f"{fi.module}:{qual}",
                    module=fi.module,
                    qualname=qual,
                    path=fi.rel,
                    lineno=node.lineno,
                    node=node,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                    cls=cls.name if cls is not None else None,
                )
                self.funcs[info.key] = info
                if cls is not None and prefix == cls.name:
                    cls.methods[node.name] = info
                    self.method_index.setdefault(node.name, []).append(
                        info
                    )
                self._walk_scope(fi, node.body, prefix=qual, cls=cls)

    # ----------------------------------------------------- type inference

    def _resolve_class_name(
        self, module: str, chain: List[str]
    ) -> Optional[ClassInfo]:
        """Resolve a constructor reference (Name or mod.Name chain) to a
        project class, through this module's imports."""
        imports = self.imports.get(module, {})
        name = chain[-1]
        if len(chain) == 1:
            # class defined in this module?
            ci = self.class_by_qual.get(f"{module}:{name}")
            if ci is not None:
                return ci
            imp = imports.get(name)
            if imp and imp[0] == "symbol":
                ci = self.class_by_qual.get(f"{imp[1]}:{imp[2]}")
                if ci is not None:
                    return ci
                # one re-export hop through a package __init__
                init = self.modules.get(imp[1])
                if init is not None:
                    sub = self.imports.get(imp[1], {}).get(imp[2])
                    if sub and sub[0] == "symbol":
                        return self.class_by_qual.get(
                            f"{sub[1]}:{sub[2]}"
                        )
            return None
        head = imports.get(chain[0])
        if head and head[0] == "module":
            mod = ".".join([head[1]] + chain[1:-1])
            return self.class_by_qual.get(f"{mod}:{name}")
        return None

    def _ctor_of(self, module: str, node) -> Optional[ClassInfo]:
        """node is a Call: project class it constructs, if any."""
        if not isinstance(node, ast.Call):
            return None
        chain = _attr_chain(node.func)
        if not chain:
            return None
        return self._resolve_class_name(module, chain)

    def _infer_types(self) -> None:
        """Attr/param types to a fixed point (3 rounds is plenty for
        the depth of composition in this tree)."""
        # param types: "module:Qual.name" -> {param: {class names}}
        self.param_types: Dict[str, Dict[str, Set[str]]] = {}
        for _ in range(3):
            changed = self._infer_round()
            if not changed:
                break

    def _infer_round(self) -> bool:
        changed = False
        self._local_cache = {}  # local types depend on param types
        for cls_list in self.classes.values():
            for ci in cls_list:
                for m in ci.methods.values():
                    changed |= self._infer_method_attrs(ci, m)
        # constructor call sites -> __init__ param types
        for info in self.funcs.values():
            fi = self.files[info.path]
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self._ctor_of(info.module, node)
                if target is None:
                    continue
                init = self.resolve_method(target.name, "__init__")
                if init is None:
                    continue
                params = [
                    a.arg for a in init.node.args.args if a.arg != "self"
                ]
                slot = self.param_types.setdefault(init.key, {})
                for i, arg in enumerate(node.args):
                    if i >= len(params):
                        break
                    for t in self._expr_types(info, arg):
                        s = slot.setdefault(params[i], set())
                        if t not in s:
                            s.add(t)
                            changed = True
                for kw in node.keywords:
                    if kw.arg is None or kw.arg not in params:
                        continue
                    for t in self._expr_types(info, kw.value):
                        s = slot.setdefault(kw.arg, set())
                        if t not in s:
                            s.add(t)
                            changed = True
        return changed

    def _infer_method_attrs(self, ci: ClassInfo, m: FuncInfo) -> bool:
        changed = False
        for node in ast.walk(m.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            value = node.value
            if value is None:
                continue
            for t in targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                for typ in self._expr_types(m, value):
                    s = ci.attr_types.setdefault(t.attr, set())
                    if typ not in s:
                        s.add(typ)
                        changed = True
                for typ in self._expr_elem_types(m, value):
                    s = ci.attr_elem_types.setdefault(t.attr, set())
                    if typ not in s:
                        s.add(typ)
                        changed = True
        return changed

    def _expr_types(self, info: FuncInfo, node) -> Set[str]:
        """Project class names an expression may evaluate to."""
        node = _strip_or_none(node)
        ci = self._ctor_of(info.module, node)
        if ci is not None:
            return {ci.name}
        # parameter or local with an inferred type
        if isinstance(node, ast.Name):
            out = set(
                self.param_types.get(info.key, {}).get(node.id, set())
            )
            if node.id not in {
                a.arg for a in info.node.args.args
            }:
                out |= self._local_types(info).get(node.id, set())
            return out
        # self.attr of the enclosing class
        chain = _attr_chain(node)
        if chain and chain[0] == "self" and len(chain) == 2 \
                and info.cls is not None:
            out = set()
            for ci2 in self.classes.get(info.cls, []):
                for c in self.class_mro(ci2):
                    out |= c.attr_types.get(chain[1], set())
            return out
        return set()

    def _expr_elem_types(self, info: FuncInfo, node) -> Set[str]:
        """Element types for list-of-T expressions."""
        node = _strip_or_none(node)
        out: Set[str] = set()
        if isinstance(node, ast.ListComp):
            ci = self._ctor_of(info.module, node.elt)
            if ci is not None:
                out.add(ci.name)
        elif isinstance(node, (ast.List, ast.Tuple)):
            for el in node.elts:
                ci = self._ctor_of(info.module, el)
                if ci is not None:
                    out.add(ci.name)
        return out

    # -------------------------------------------------------- call graph

    def class_mro(self, ci: ClassInfo) -> List[ClassInfo]:
        out, seen = [ci], {ci.name}
        queue = list(ci.bases)
        while queue:
            b = queue.pop(0)
            if b in seen:
                continue
            seen.add(b)
            for cand in self.classes.get(b, []):
                out.append(cand)
                queue.extend(cand.bases)
        return out

    def resolve_method(
        self, cls_name: str, method: str
    ) -> Optional[FuncInfo]:
        for ci in self.classes.get(cls_name, []):
            for c in self.class_mro(ci):
                if method in c.methods:
                    return c.methods[method]
        return None

    def resolve_module_func(self, module: str,
                            name: str) -> Optional[FuncInfo]:
        """`module.name` as a function, following ONE package-__init__
        re-export hop (`emqx_tpu.fault:inject` ->
        `emqx_tpu.fault.plane:inject`)."""
        cand = self.funcs.get(f"{module}:{name}")
        if cand is not None:
            return cand
        imp = self.imports.get(module, {}).get(name)
        if imp and imp[0] == "symbol":
            return self.funcs.get(f"{imp[1]}:{imp[2]}")
        if imp and imp[0] == "module":
            return None
        return None

    def _resolve_call_targets(
        self, info: FuncInfo, func_node
    ) -> List[FuncInfo]:
        """Best-effort: every FuncInfo a call/callable-reference may
        land in (multiple when a receiver type is ambiguous)."""
        func_node = _unwrap_callable(func_node)
        if isinstance(func_node, ast.Lambda):
            return []  # body is inline; callers' role covers it
        chain = _attr_chain(func_node)
        if not chain:
            return []
        imports = self.imports.get(info.module, {})
        if len(chain) == 1:
            name = chain[0]
            # nested def inside this function
            cand = self.funcs.get(f"{info.module}:{info.qualname}.{name}")
            if cand is not None:
                return [cand]
            # sibling nested def (shared enclosing function)
            if "." in info.qualname:
                parent = info.qualname.rsplit(".", 1)[0]
                cand = self.funcs.get(f"{info.module}:{parent}.{name}")
                if cand is not None:
                    return [cand]
            # module-level function
            cand = self.funcs.get(f"{info.module}:{name}")
            if cand is not None:
                return [cand]
            # constructor -> __init__
            ci = self._resolve_class_name(info.module, chain)
            if ci is not None:
                init = self.resolve_method(ci.name, "__init__")
                return [init] if init is not None else []
            imp = imports.get(name)
            if imp and imp[0] == "symbol":
                cand = self.funcs.get(f"{imp[1]}:{imp[2]}")
                if cand is None:
                    # one more hop through a package __init__
                    cand = self.resolve_module_func(imp[1], imp[2])
                if cand is not None:
                    return [cand]
            return []
        # attribute call: receiver . method
        method = chain[-1]
        recv = chain[:-1]
        out: List[FuncInfo] = []
        for t in sorted(self._receiver_types(info, recv)):
            got = self.resolve_method(t, method)
            if got is not None:
                out.append(got)
        if out:
            return out
        # module attribute: mod.fn() (with package-__init__ hop)
        head = imports.get(recv[0])
        if head and head[0] == "module":
            mod = ".".join([head[1]] + recv[1:])
            cand = self.resolve_module_func(mod, method)
            if cand is not None:
                return [cand]
            ci2 = self._resolve_class_name(info.module, chain)
            if ci2 is not None:
                init = self.resolve_method(ci2.name, "__init__")
                return [init] if init is not None else []
        # constructor via module chain (mod.Class())
        ci3 = self._resolve_class_name(info.module, chain)
        if ci3 is not None:
            init = self.resolve_method(ci3.name, "__init__")
            return [init] if init is not None else []
        # unique-method fallback
        if method not in _GENERIC_METHODS and not method.startswith("__"):
            cands = self.method_index.get(method, [])
            if len(cands) == 1:
                return [cands[0]]
        return []

    def _receiver_types(
        self, info: FuncInfo, recv: List[str]
    ) -> Set[str]:
        """Project class names `recv` (attr chain w/o the method) may
        hold.  Walks self.attr(.attr)* through inferred attr types;
        Subscript receivers are pre-flattened by the edge builder."""
        types: Set[str] = set()
        if recv[0] == "self" and info.cls is not None:
            types = {info.cls}
            rest = recv[1:]
        else:
            # local variable / parameter types
            pt = self.param_types.get(info.key, {})
            types = set(pt.get(recv[0], set()))
            types |= self._local_types(info).get(recv[0], set())
            rest = recv[1:]
            if not types:
                return set()
        for attr in rest:
            nxt: Set[str] = set()
            for t in types:
                for ci in self.classes.get(t, []):
                    for c in self.class_mro(ci):
                        nxt |= c.attr_types.get(attr, set())
            types = nxt
            if not types:
                break
        return types

    def _local_types(self, info: FuncInfo) -> Dict[str, Set[str]]:
        cache = getattr(self, "_local_cache", None)
        if cache is None:
            cache = self._local_cache = {}
        got = cache.get(info.key)
        if got is not None:
            return got
        # publish the (initially empty) dict BEFORE filling it: a local
        # assigned from another local would otherwise recurse forever
        out: Dict[str, Set[str]] = {}
        cache[info.key] = out
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                for t in self._expr_types(info, node.value):
                    out.setdefault(name, set()).add(t)
                for t in self._expr_elem_types(info, node.value):
                    out.setdefault(f"{name}[]", set()).add(t)
        cache[info.key] = out
        return out

    def _subscript_elem_types(
        self, info: FuncInfo, node
    ) -> Set[str]:
        """Types of x[i] / self.attr[i] receivers via elem-type info."""
        base = node.value
        chain = _attr_chain(base)
        if not chain:
            return set()
        if chain[0] == "self" and info.cls is not None and len(chain) == 2:
            out: Set[str] = set()
            for ci in self.classes.get(info.cls, []):
                for c in self.class_mro(ci):
                    out |= c.attr_elem_types.get(chain[1], set())
            return out
        if len(chain) == 1:
            return self._local_types(info).get(f"{chain[0]}[]", set())
        return set()

    def _build_edges(self) -> None:
        for info in self.funcs.values():
            for node in _walk_own_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                self._edge_from_call(info, node)

    def _edge_from_call(self, info: FuncInfo, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        attr = chain[-1] if chain else None
        # executor hops: asyncio.to_thread(f, ...) /
        # loop.run_in_executor(pool, f, ...) / Thread(target=f) /
        # pool_executor.submit(f, ...)
        if attr == "to_thread" and node.args:
            self._add_callable_edge(info, node.args[0], EXECUTOR,
                                    node.lineno)
            return
        if attr == "run_in_executor" and len(node.args) >= 2:
            self._add_callable_edge(info, node.args[1], EXECUTOR,
                                    node.lineno)
            return
        if attr == "Thread" or (chain == ["Thread"]):
            for kw in node.keywords:
                if kw.arg == "target":
                    self._add_callable_edge(info, kw.value, EXECUTOR,
                                            node.lineno)
            return
        if attr in ("create_task", "ensure_future") and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                self._add_callable_edge(info, inner.func, TASK,
                                        node.lineno)
            else:
                self._add_callable_edge(info, inner, TASK, node.lineno)
            # fall through: the create_task(...) call itself is loop-side
        # subscript receiver: self.buffers[k].append(...)
        if (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Subscript)
        ):
            hit = False
            for t in self._subscript_elem_types(info, node.func.value):
                got = self.resolve_method(t, node.func.attr)
                if got is not None:
                    self.edges.append(
                        Edge(info.key, got.key, CALL, node.lineno)
                    )
                    hit = True
            if hit:
                return
        for target in self._resolve_call_targets(info, node.func):
            self.edges.append(
                Edge(info.key, target.key, CALL, node.lineno)
            )

    def _add_callable_edge(self, info: FuncInfo, expr, kind: str,
                           lineno: int) -> None:
        expr = _unwrap_callable(expr)
        if isinstance(expr, ast.Lambda):
            # lambda body runs wherever the hop lands: synthesize no
            # function, but resolve calls inside the lambda directly
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    for tgt in self._resolve_call_targets(info, sub.func):
                        self.edges.append(
                            Edge(info.key, tgt.key, kind, lineno)
                        )
                        if kind == EXECUTOR:
                            self.executor_targets.add(tgt.key)
            return
        for target in self._resolve_call_targets(info, expr):
            self.edges.append(Edge(info.key, target.key, kind, lineno))
            if kind == EXECUTOR:
                self.executor_targets.add(target.key)


def _strip_or_none(node):
    """`x or Default()` / `Default() if c else None` -> the ctor arm."""
    if isinstance(node, ast.BoolOp):
        for v in node.values:
            if isinstance(v, ast.Call):
                return v
    if isinstance(node, ast.IfExp):
        if isinstance(node.body, ast.Call):
            return node.body
        if isinstance(node.orelse, ast.Call):
            return node.orelse
    return node


def _walk_own_body(fn):
    """Walk a function's body WITHOUT descending into nested defs or
    classes (they are their own FuncInfo scopes); lambdas stay — their
    bodies execute in this frame (or wherever the reference lands,
    handled at the hop sites)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if _is_def(n) or isinstance(n, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _iter_py(repo: str, targets: List[str]):
    for t in targets:
        p = os.path.join(repo, t)
        if os.path.isfile(p):
            yield t
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.relpath(
                        os.path.join(root, f), repo
                    )
