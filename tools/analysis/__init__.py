"""Concurrency-aware static-analysis framework (`make check`).

The reference broker runs dialyzer/xref/elvis as part of the build
(`rebar.config`); neither ships in this image and installs are
off-limits, so this package implements the same three analyses —
whole-program success-typing-style inference, cross-reference checking,
and style lints — directly on the stdlib, specialized to the four
concurrency domains this codebase actually has (asyncio event loop,
executor worker threads, the persistent native worker pool, and the
GIL-free churn plane).

Shared substrate (`index.py`): ONE parse of the whole tree into an AST
index + call graph, including `asyncio.create_task` /
`run_in_executor` / `to_thread` / `threading.Thread` edges and method
resolution through `self` and constructor-inferred attribute types.
Every pass below runs on that index.

Pass -> reference analog:

* **roles + blocking-call detector** (`roles.py`) — the dialyzer
  analog: like success typings propagated from known roots, thread
  roles (loop / worker / pool) propagate from `async def`, executor
  targets and native pool entry points through the call graph; a
  blocking primitive (`time.sleep`, `os.fsync`, file writes,
  `subprocess.*`, blocking `Lock.acquire`, socket ops) reachable on
  the loop role without an executor hop is the moral equivalent of a
  dialyzer "will never return" contract violation.  This pass
  rediscovers PR 4 fix #3 (`time.sleep` fault action freezing the
  loop) and PR 5 fix #2 (fsync-heavy GC on the wrong thread) from
  their pre-fix shapes — both are encoded as regression fixtures in
  tests/test_analysis.py.
* **cross-thread state lint** (`races.py`) — the dialyzer race
  detector (`-Wrace_conditions`) analog: `self.<attr>` written from
  two roles (or written off-loop, read on-loop) must be guarded by one
  consistently-held `threading.Lock` or carry an explicit
  `# analysis: owner=<role>` annotation; `await` under a held
  threading lock is flagged unconditionally.
* **registry cross-checks** (`registry.py`) — the xref analog
  (undefined-function-calls + unused-exports, both directions): config
  keys vs SCHEMA, metrics counters vs PREDEFINED, alarm
  activate/deactivate pairing, tracepoints vs KNOWN_KINDS (including
  dead registrations), fault sites vs SITES.
* **style lints** (`lints.py`) — the elvis analog: the original checks
  #1-#4 and #8 (syntax, undefined names, unused imports/dup
  defs/mutable defaults/bare except, `g++ -fsyntax-only`, churn-WAL
  hook coverage), ported onto the shared index.
* **lock-order analysis** (`locks.py`) — deadlock freedom: per-lock
  identities, held-set tracking through `with`/`acquire` and the call
  graph, cycle detection, the blessed global order in
  `lockorder.json`, non-reentrant self-deadlocks, and awaits under
  split-guard (non-lexical) threading locks.
* **task/resource lifecycle** (`lifecycle.py`) — every
  `create_task`/`ensure_future` retained + cancel-reachable from
  teardown, file/socket/executor handles closed, hook and single-slot
  callback registrations paired with their unregister.
* **cancellation safety** (`cancel.py`) — swallowed `CancelledError`
  (outside the cancel-then-join reap idiom) and `finally`-less paired
  mutations around an `await`.

Severity tiers: `error` fails always; `warn` fails unless
grandfathered in the committed `baseline.json` (`baseline.py`).
`python -m tools.analysis --json` emits machine-readable findings;
`--changed` limits per-file passes to `git diff` files; `--only
<pass>` runs one pass; `--stats` prints per-pass node/edge counts.
Stdlib-only.

Annotations (all in source comments, linted for well-formedness):

* ``# analysis: owner=<role>``       — deliberate single-owner attr
* ``# analysis: allow-blocking(<why>)`` — deliberate blocking call
* ``# analysis: lock-after=<name>``  — reviewed lock-order exception
* ``# analysis: detached-task(<why>)`` — deliberate fire-and-forget
* ``# analysis: lifetime=node(<why>)`` — process-lifetime callback
* ``# check: ignore``                — suppress any finding on a line
"""

from .index import ProjectIndex  # noqa: F401
from .report import ERROR, WARN, Finding, Report  # noqa: F401
