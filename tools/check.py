"""Static-analysis gate (`make check`) — stdlib-only by necessity.

The reference treats dialyzer/xref/elvis as part of the build
(`/root/reference/rebar.config:16-36`); the analog here would be mypy +
ruff, but neither ships in this image and installs are off-limits, so
this gate implements the highest-value checks directly on the stdlib:

  1. syntax: every .py compiles (py_compile)
  2. undefined names: symtable resolves bindings per scope; a name
     referenced as an implicit global that is bound neither at module
     scope nor in builtins is a NameError waiting for its code path
     (pyflakes' core check)
  3. AST lints: unused imports, duplicate top-level/class-level defs,
     mutable default arguments, bare `except:`
  4. native layer: g++ -fsyntax-only -Wall -Wextra over native/*.cc
  5. tracepoint registry: every `tp("<kind>", ...)` emitted from
     production code (emqx_tpu/**) must be registered in
     `observe/tracepoints.py` KNOWN_KINDS — dashboards and trace
     consumers key on these names, so an unregistered kind is an event
     nobody can subscribe to by contract (tests may emit ad-hoc kinds)
  6. fault-site registry: every `fault.inject("<site>", ...)` (and
     ainject/peek/mangle) in emqx_tpu/** must name a site registered in
     `fault/sites.py` SITES — chaos schedules key on these names, and
     an unregistered site can never be armed from config
  7. ds config schema: every `ds.*` config key read in emqx_tpu/ds/
     (any `.get("ds.<key>")` literal) must be declared in the config
     schema (`config/config.py` SCHEMA["ds"]) — the inverse direction
     of the dead-config audit: a key read but never declared always
     resolves to None and silently disables what it configures
  8. churn WAL hook coverage: every PUBLIC mutation path of the two
     match engines (TopicMatchEngine / ShardedMatchEngine) that touches
     table or churn-plane state must reference the `on_churn` hook —
     a mutator that skips the hook silently diverges the checkpoint
     WAL from host truth (checkpoint/wal.py's exactly-once replay
     contract).  Private helpers delegate the hook to their public
     callers; rollback code inside `except` blocks is exempt; an
     `on_churn` CALL inside a loop is flagged too (the WAL contract is
     one serialized record per mutation batch, not per item)

Exit code 0 = clean.  `--fix` is intentionally absent: findings are
either real bugs or deliberate (suppressed via `# check: ignore` on the
offending line).
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
import symtable
import sysconfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ["emqx_tpu", "tests", "tools", "bench.py", "__graft_entry__.py"]

# names bound at runtime in ways symtable cannot see
_KNOWN_GLOBALS = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
    "WindowsError",  # guarded platform use
}


def _py_files():
    for t in TARGETS:
        p = os.path.join(REPO, t)
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _ignored_lines(src: str):
    return {
        i + 1
        for i, line in enumerate(src.splitlines())
        if "# check: ignore" in line
    }


def _walk_tables(tab, out):
    out.append(tab)
    for child in tab.get_children():
        _walk_tables(child, out)


def check_undefined(path, src, tree, problems, ignored):
    import builtins

    try:
        top = symtable.symtable(src, path, "exec")
    except SyntaxError:
        return
    module_names = set(_KNOWN_GLOBALS)
    for sym in top.get_symbols():
        module_names.add(sym.get_name())
    # names star-imported or assigned via exec can't be tracked; skip
    # modules using either
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            return
    tabs = []
    _walk_tables(top, tabs)
    bi = set(dir(builtins))
    # line numbers for name loads, gathered once from the AST
    loads = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.setdefault(node.id, node.lineno)
    for tab in tabs[1:]:  # skip module scope: handled via module_names
        for sym in tab.get_symbols():
            name = sym.get_name()
            if not sym.is_referenced() or sym.is_assigned():
                continue
            if sym.is_parameter() or sym.is_imported():
                continue
            if sym.is_free():  # bound in an enclosing function scope
                continue
            if name in module_names or name in bi:
                continue
            line = loads.get(name, tab.get_lineno())
            if line in ignored:
                continue
            problems.append(
                f"{path}:{line}: undefined name {name!r} "
                f"(in {tab.get_name()})"
            )


def check_ast_lints(path, src, tree, problems, ignored):
    # unused imports (module scope only; conservative: any attribute or
    # name use of the bound name counts, and re-export files are skipped)
    base = os.path.basename(path)
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the Name node below it is what binds
    all_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for el in node.value.elts:
                            if isinstance(el, ast.Constant):
                                all_names.add(el.value)
    if base != "__init__.py":  # __init__ re-export surfaces are the API
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                if isinstance(node, ast.ImportFrom) \
                        and node.module == "__future__":
                    continue
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    if a.name == "*" or name.startswith("_"):
                        continue
                    if name not in used and name not in all_names \
                            and node.lineno not in ignored:
                        problems.append(
                            f"{path}:{node.lineno}: unused import {name!r}"
                        )
    # duplicate defs, mutable defaults, bare except
    def dup_scan(body, scope):
        seen = {}
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                prev = seen.get(node.name)
                # property/setter & overload pairs share a name legally
                decs = {
                    d.attr if isinstance(d, ast.Attribute)
                    else getattr(d, "id", None)
                    for d in getattr(node, "decorator_list", [])
                }
                if prev is not None and not decs & {"setter", "getter",
                                                    "deleter", "overload"}:
                    if node.lineno not in ignored:
                        problems.append(
                            f"{path}:{node.lineno}: duplicate definition "
                            f"of {node.name!r} in {scope} "
                            f"(first at line {prev})"
                        )
                seen[node.name] = node.lineno
                if isinstance(node, ast.ClassDef):
                    dup_scan(node.body, f"class {node.name}")

    dup_scan(tree.body, "module")
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) \
                        and node.lineno not in ignored:
                    problems.append(
                        f"{path}:{node.lineno}: mutable default argument "
                        f"in {node.name!r}"
                    )
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None and node.lineno not in ignored:
                problems.append(
                    f"{path}:{node.lineno}: bare `except:` (catches "
                    "SystemExit/KeyboardInterrupt)"
                )


def known_tp_kinds():
    """KNOWN_KINDS keys, parsed statically from observe/tracepoints.py
    (no package import: this gate must run on a broken tree)."""
    path = os.path.join(REPO, "emqx_tpu", "observe", "tracepoints.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), path)
    for node in ast.walk(tree):
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if (
            isinstance(tgt, ast.Name)
            and tgt.id == "KNOWN_KINDS"
            and isinstance(node.value, ast.Dict)
        ):
            return {
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


def collect_tp_calls():
    """(path, lineno, kind) for every literal-kind tp(...) call in the
    emqx_tpu package."""
    out = []
    pkg = os.path.join(REPO, "emqx_tpu")
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), path)
                except SyntaxError:
                    continue  # reported by the syntax pass
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = (
                    fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None
                )
                if (
                    name == "tp"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.append((path, node.lineno, node.args[0].value))
    return out


def check_tracepoints(problems):
    known = known_tp_kinds()
    if not known:
        problems.append(
            "emqx_tpu/observe/tracepoints.py: KNOWN_KINDS registry missing"
        )
        return
    for path, line, kind in collect_tp_calls():
        if kind not in known:
            problems.append(
                f"{path}:{line}: tp kind {kind!r} not registered in "
                "observe/tracepoints.py KNOWN_KINDS"
            )


# the retained device-index plane (ISSUE 7): retained.* tracepoints are
# owned by exactly these two modules, and every registered retained.*
# kind must actually be emitted — a dead registration means the
# observability the flip depends on silently fell off a rewrite
RETAINED_TP_FILES = (
    os.path.join("emqx_tpu", "models", "retained.py"),
    os.path.join("emqx_tpu", "broker", "retainer.py"),
)


def check_retained_tracepoints(problems):
    known = {k for k in known_tp_kinds() if k.startswith("retained.")}
    emitted = {}
    for path, line, kind in collect_tp_calls():
        if not kind.startswith("retained."):
            continue
        emitted.setdefault(kind, []).append((path, line))
        rel = os.path.relpath(path, REPO)
        if rel not in RETAINED_TP_FILES:
            problems.append(
                f"{path}:{line}: retained.* tracepoint {kind!r} emitted "
                "outside models/retained.py / broker/retainer.py"
            )
    for kind in sorted(known - set(emitted)):
        problems.append(
            "emqx_tpu/observe/tracepoints.py: registered kind "
            f"{kind!r} is never emitted from models/retained.py or "
            "broker/retainer.py"
        )


FAULT_FNS = {"inject", "ainject", "peek", "mangle"}


def known_fault_sites():
    """SITES keys, parsed statically from fault/sites.py (no import)."""
    path = os.path.join(REPO, "emqx_tpu", "fault", "sites.py")
    if not os.path.isfile(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), path)
    for node in ast.walk(tree):
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if (
            isinstance(tgt, ast.Name)
            and tgt.id == "SITES"
            and isinstance(node.value, ast.Dict)
        ):
            return {
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


def collect_fault_calls():
    """(path, lineno, site) for every `fault.<fn>("<site>", ...)` /
    `_fault.<fn>(...)` call in the package (the fault package itself is
    the implementation and is exempt)."""
    out = []
    pkg = os.path.join(REPO, "emqx_tpu")
    skip = os.path.join(pkg, "fault")
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        if root.startswith(skip):
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), path)
                except SyntaxError:
                    continue  # reported by the syntax pass
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in FAULT_FNS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("fault", "_fault")
                ):
                    continue
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.append((path, node.lineno, node.args[0].value))
                else:
                    out.append((path, node.lineno, None))  # non-literal
    return out


def check_fault_sites(problems):
    known = known_fault_sites()
    calls = collect_fault_calls()
    if calls and not known:
        problems.append(
            "emqx_tpu/fault/sites.py: SITES registry missing"
        )
        return
    for path, line, site in calls:
        if site is None:
            problems.append(
                f"{path}:{line}: fault call with a non-literal site "
                "(the registry lint needs a string literal)"
            )
        elif site not in known:
            problems.append(
                f"{path}:{line}: fault site {site!r} not registered in "
                "emqx_tpu/fault/sites.py SITES"
            )


def known_ds_config_keys():
    """SCHEMA["ds"] keys, parsed statically from config/config.py."""
    path = os.path.join(REPO, "emqx_tpu", "config", "config.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), path)
    for node in ast.walk(tree):
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if not (
            isinstance(tgt, ast.Name)
            and tgt.id == "SCHEMA"
            and isinstance(node.value, ast.Dict)
        ):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == "ds"
                and isinstance(v, ast.Dict)
            ):
                return {
                    f"ds.{f.value}"
                    for f in v.keys
                    if isinstance(f, ast.Constant)
                    and isinstance(f.value, str)
                }
    return set()


def collect_ds_config_reads():
    """(path, lineno, key) for every `<x>.get("ds.<key>", ...)` literal
    in the emqx_tpu/ds/ package."""
    out = []
    pkg = os.path.join(REPO, "emqx_tpu", "ds")
    if not os.path.isdir(pkg):
        return out
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), path)
                except SyntaxError:
                    continue  # reported by the syntax pass
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not (isinstance(fn, ast.Attribute) and fn.attr == "get"):
                    continue
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("ds.")
                ):
                    out.append((path, node.lineno, node.args[0].value))
    return out


def check_ds_config(problems):
    reads = collect_ds_config_reads()
    if not reads:
        return
    known = known_ds_config_keys()
    if not known:
        problems.append(
            "emqx_tpu/config/config.py: SCHEMA has no 'ds' namespace but "
            "emqx_tpu/ds/ reads ds.* config keys"
        )
        return
    for path, line, key in reads:
        if key not in known:
            problems.append(
                f"{path}:{line}: config key {key!r} read but not declared "
                "in config/config.py SCHEMA['ds']"
            )


ENGINE_CLASSES = {
    os.path.join("emqx_tpu", "models", "engine.py"): {"TopicMatchEngine"},
    os.path.join("emqx_tpu", "parallel", "sharded.py"): {
        "ShardedMatchEngine"
    },
}
TABLE_MUTATORS = {
    "insert", "delete", "delete_batch", "churn_insert",
    "churn_insert_keys", "bulk_insert", "bulk_insert_keys",
    "apply_planned",
}
PLANE_HELPERS = {"_plane_churn", "_plane_apply"}
CHURN_HOOK_EXEMPT = {"restore_checkpoint"}  # state adoption, not churn


def _subtree_names(node):
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _walk_outside_except(node):
    """Walk a function body skipping `except` handler subtrees (rollback
    paths legitimately undo mutations without firing the hook)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.ExceptHandler):
                continue
            stack.append(child)


def _method_mutates(fn) -> bool:
    """True when fn's body (outside except blocks) calls a table/plane
    mutator on self state."""
    for n in _walk_outside_except(fn):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr in TABLE_MUTATORS:
            names = _subtree_names(f.value)
            if "tables" in names or "shards" in names:
                return True
        elif f.attr == "apply":
            if isinstance(f.value, ast.Attribute) \
                    and f.value.attr == "_plane":
                return True
        elif f.attr in PLANE_HELPERS:
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                return True
    return False


def check_churn_hooks(problems):
    for rel, classes in ENGINE_CLASSES.items():
        path = os.path.join(REPO, rel)
        if not os.path.isfile(path):
            continue
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, path)
        except SyntaxError:
            continue  # reported by the syntax pass
        ignored = _ignored_lines(src)
        for cls in ast.walk(tree):
            if not (isinstance(cls, ast.ClassDef) and cls.name in classes):
                continue
            methods = [
                n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            mutating = {m.name for m in methods if _method_mutates(m)}
            private_mut = {m for m in mutating if m.startswith("_")}
            for m in methods:
                if m.name.startswith("_") or m.name in CHURN_HOOK_EXEMPT:
                    continue
                direct = m.name in mutating
                via_helper = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in private_mut
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self"
                    for n in _walk_outside_except(m)
                )
                if not (direct or via_helper):
                    continue
                refs_hook = any(
                    isinstance(n, ast.Attribute) and n.attr == "on_churn"
                    for n in ast.walk(m)
                )
                if not refs_hook and m.lineno not in ignored:
                    problems.append(
                        f"{path}:{m.lineno}: {cls.name}.{m.name} mutates "
                        "match-table/churn-plane state without firing the "
                        "on_churn WAL hook"
                    )
                # the hook must fire once per batch, never per item
                for n in ast.walk(m):
                    if not isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
                        continue
                    for c in ast.walk(n):
                        if (
                            isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr == "on_churn"
                            and c.lineno not in ignored
                        ):
                            problems.append(
                                f"{path}:{c.lineno}: {cls.name}.{m.name} "
                                "calls on_churn inside a loop (WAL records "
                                "are one per mutation batch)"
                            )


def check_native(problems):
    src_dir = os.path.join(REPO, "native")
    if not os.path.isdir(src_dir):
        return
    srcs = sorted(
        os.path.join(src_dir, f)
        for f in os.listdir(src_dir)
        if f.endswith(".cc")
    )
    inc = sysconfig.get_paths().get("include") or ""
    for s in srcs:
        cmd = ["g++", "-fsyntax-only", "-Wall", "-Wextra",
               "-Wno-unused-parameter", "-std=c++17", "-march=native"]
        if inc:
            cmd.append(f"-I{inc}")
        r = subprocess.run(cmd + [s], capture_output=True, text=True,
                           timeout=120)
        if r.returncode != 0 or r.stderr.strip():
            problems.append(f"{s}: g++ -Wall -Wextra:\n{r.stderr.strip()}")


def main() -> int:
    problems = []
    n = 0
    for path in _py_files():
        n += 1
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, path)
        except SyntaxError as e:
            problems.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
            continue
        ignored = _ignored_lines(src)
        check_undefined(path, src, tree, problems, ignored)
        check_ast_lints(path, src, tree, problems, ignored)
    check_tracepoints(problems)
    check_retained_tracepoints(problems)
    check_fault_sites(problems)
    check_ds_config(problems)
    check_churn_hooks(problems)
    check_native(problems)
    for p in problems:
        print(p)
    print(f"\nchecked {n} python files + native/*.cc: "
          f"{len(problems)} finding(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
