"""Phase breakdown of the mesh-sharded engine's match tick (VERDICT r4
#5: WHERE do the milliseconds go on the 8-virtual-device CPU mesh?).

Phases per tick:
  prep      — host words/hash + replicated device_put of the topic batch
  dispatch  — the pjit'd mesh computation (block_until_ready)
  fetch     — device->host of the compact [D, B, k] hits + counts
  verify    — registry-backed exact verification + row assembly

Run: python tools/profile_sharded.py [--subs 100000] [--ticks 512,4096]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=100_000)
    ap.add_argument("--ticks", default="512,4096")
    ap.add_argument("--iters", type=int, default=20)
    ns = ap.parse_args()

    import gc
    import importlib.util
    import random

    spec = importlib.util.spec_from_file_location(
        "benchmod", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from emqx_tpu.parallel.sharded import ShardedMatchEngine
    from emqx_tpu.parallel import sharded as shmod

    rng = random.Random(1236)
    filters, topics_fn = bench.pop_wild_100k(rng, ns.subs)
    eng = ShardedMatchEngine(kcap=64)
    t0 = time.time()
    eng.add_filters(filters)
    print(f"insert {len(filters)/(time.time()-t0):,.0f}/s over {eng.D} "
          f"devices", file=sys.stderr)
    gc.collect()
    gc.freeze()

    for tick in (int(x) for x in ns.ticks.split(",")):
        batches = [topics_fn()[:tick] for _ in range(6)]
        eng.match(batches[0])  # compile
        eng.match(batches[1])
        prep_s = disp_s = fetch_s = verify_s = 0.0
        lat = []
        for i in range(ns.iters):
            topics = batches[i % 6]
            b0 = time.perf_counter()
            p0 = time.perf_counter()
            batch, n = eng._prep_batch(topics)
            p1 = time.perf_counter()
            hits, counts = shmod.sharded_match_compact(
                eng._stacked, batch, mesh=eng.mesh, kcap=eng.kcap
            )
            jax.block_until_ready((hits, counts))
            p2 = time.perf_counter()
            h = np.asarray(hits)[:, :n, :]
            c = np.asarray(counts)[:, :n]
            p3 = time.perf_counter()
            pend = shmod._ShardedPending(
                hits, counts, eng._stacked, n, list(topics), None
            )
            out = eng.match_collect_raw(pend)
            p4 = time.perf_counter()
            prep_s += p1 - p0
            disp_s += p2 - p1
            fetch_s += p3 - p2
            verify_s += p4 - p3
            lat.append(p4 - b0)
        it = ns.iters
        a = np.array(lat) * 1e3
        print(
            f"tick {tick:5d}: prep {prep_s/it*1e3:7.2f}  "
            f"dispatch {disp_s/it*1e3:7.2f}  fetch {fetch_s/it*1e3:7.2f}  "
            f"verify+asm {verify_s/it*1e3:7.2f} ms | "
            f"p50 {np.percentile(a,50):.1f} p99 {np.percentile(a,99):.1f} ms "
            f"-> {it*tick/sum(lat):,.0f} lookups/s"
        )


if __name__ == "__main__":
    main()
