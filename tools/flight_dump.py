"""Human-readable dump of an engine flight recorder.

The recorder (`emqx_tpu/observe/flight.py`) rings one struct per match
tick: path served, arbitration reason, EWMA rates at decision time, wire
bytes up/down, verify mismatches, and churn lag.  This tool renders two
views:

* a recent-tick table (newest last) — what the engine actually did,
  tick by tick;
* the arbitration-flip timeline — every host<->device switch still in
  the ring, with the reason and the rates that drove it.

Input is a pickled recorder (``FlightRecorder.save(path)`` from a REPL,
a debug endpoint, or a bench run) — or, from Python, call
:func:`dump` directly on a LIVE recorder object::

    from tools.flight_dump import dump
    print(dump(node.broker.engine.flight))

Usage:
    python tools/flight_dump.py flight.pkl            # both views
    python tools/flight_dump.py flight.pkl -n 100     # more ticks
    python tools/flight_dump.py flight.pkl --flips    # timeline only
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from emqx_tpu.observe.flight import FlightRecorder  # noqa: E402


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GB"


def _fmt_rate(r: float) -> str:
    return "-" if not r else f"{r:,.0f}"


def _fmt_occ(r: dict) -> str:
    """Pipeline occupancy at submit as occ/depth ('-' before PR 2 rings
    or engines that never set the fields)."""
    if not r.get("pipe_depth"):
        return "-"
    return f"{r['pipe_occ']}/{r['pipe_depth']}"


def format_ticks(rec: FlightRecorder, n: int = 32) -> str:
    """The last `n` tick records as an aligned table (oldest first)."""
    rows = rec.recent(n)
    if not rows:
        return "(no ticks recorded)"
    hdr = (f"{'tick':>8} {'path':>6} {'reason':<12} {'n':>6} {'uniq':>6} "
           f"{'occ':>5} {'lat ms':>9} {'p.hash':>7} {'p.pack':>7} "
           f"{'p.sub':>7} {'memo':>6} {'grp':>3} {'up':>9} {'down':>9} "
           f"{'rate_h':>12} {'rate_d':>12} {'vfail':>5} {'churn':>7} "
           f"{'shed':>7}")
    lines = [hdr, "-" * len(hdr)]
    first_tick = rec.n - len(rows)
    for i, r in enumerate(rows):
        lines.append(
            f"{first_tick + i:>8} {r['path']:>6} "
            f"{(r['reason'] or '-') + ('*' if r['flip'] else ''):<12} "
            f"{r['n_topics']:>6} {r['n_unique']:>6} "
            f"{_fmt_occ(r):>5} {r['lat_ms']:>9.3f} "
            f"{r.get('prep_hash_ms', 0):>7.3f} "
            f"{r.get('prep_pack_ms', 0):>7.3f} "
            f"{r.get('prep_submit_ms', 0):>7.3f} "
            f"{r.get('memo_hits', 0):>6} "
            f"{r.get('prep_group', 0):>3} "
            f"{_fmt_bytes(r['bytes_up']):>9} "
            f"{_fmt_bytes(r['bytes_down']):>9} "
            f"{_fmt_rate(r['rate_host']):>12} "
            f"{_fmt_rate(r['rate_dev']):>12} "
            f"{r['verify_fail']:>5} {r['churn_slots']:>7} "
            f"{r.get('churn_shed', 0):>7}"
        )
    lines.append("(* = arbitration flip on this tick; occ = pipeline "
                 "occupancy at submit / window depth; p.hash/p.pack/"
                 "p.sub = fused-prep sub-stage ms; memo = topic-memo "
                 "hits this tick; grp = coalesced-dispatch group size)")
    return "\n".join(lines)


def format_flips(rec: FlightRecorder) -> str:
    """Arbitration-flip timeline (every path switch still in the ring)."""
    flips = rec.flips()
    head = (f"{rec.path_flips} flip(s) total, {len(flips)} in ring "
            f"({rec.host_ticks} host / {rec.dev_ticks} device ticks)")
    if not flips:
        return head
    lines = [head]
    for f in flips:
        lines.append(
            f"  t={f['ts']:.3f}  -> {f['path']:<6} reason={f['reason']:<12} "
            f"rate_host={_fmt_rate(f['rate_host'])} "
            f"rate_dev={_fmt_rate(f['rate_dev'])} "
            f"lat={f['lat_ms']:.3f} ms"
        )
    return "\n".join(lines)


def dump(rec: FlightRecorder, n: int = 32, flips_only: bool = False) -> str:
    """Both views as one string (works on a live recorder)."""
    parts = []
    if not flips_only:
        s = rec.summary()
        parts.append(
            f"flight recorder: {s['ticks']} tick(s), ring {s['ring_size']}, "
            f"bytes up={_fmt_bytes(s['bytes_up'])} "
            f"down={_fmt_bytes(s['bytes_down'])}, "
            f"verify mismatches {s['verify_mismatch']}"
        )
        parts.append("")
        parts.append(format_ticks(rec, n))
        parts.append("")
    parts.append(format_flips(rec))
    return "\n".join(parts)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="dump a pickled engine flight recorder")
    ap.add_argument("path", help="pickled FlightRecorder "
                                 "(FlightRecorder.save / pickle.dump)")
    ap.add_argument("-n", type=int, default=32,
                    help="recent ticks to show (default 32)")
    ap.add_argument("--flips", action="store_true",
                    help="arbitration-flip timeline only")
    ns = ap.parse_args()
    rec = FlightRecorder.load(ns.path)
    print(dump(rec, n=ns.n, flips_only=ns.flips))
    return 0


if __name__ == "__main__":
    sys.exit(main())
