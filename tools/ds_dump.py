"""Inspect a durable-message-log directory (shard segment chains).

Usage:
    python tools/ds_dump.py <ds-dir>              # <data_dir>/ds
    python tools/ds_dump.py <ds-dir>/shard-0      # one shard
    python tools/ds_dump.py <file.log|.open>      # one segment file
    python tools/ds_dump.py <ds-dir> --records 5  # peek 5 records/shard

Prints, per shard: the segment chain (generation, base offset, record
count, size, sealed/active, frame verdict), total bytes, and the offset
span; with --records, decodes the newest records (topic, qos, payload
size, age).  Symmetric with `tools/ckpt_dump.py` for the checkpoint
plane.  Reads only — safe against a live node's directory (sealed
segments are immutable; the active-segment scan uses the same
torn-tail-tolerant reader as recovery).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from emqx_tpu.ds.log import (  # noqa: E402
    _HDR,
    _REC,
    MAX_RECORD,
    SegmentError,
    _scan_segment,
)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n} B"


def dump_segment(path: str) -> dict:
    size = os.path.getsize(path)
    sealed = path.endswith(".log")
    try:
        (shard, gen, base, count), good = _scan_segment(path)
    except (SegmentError, OSError) as e:
        print(f"  {os.path.basename(path):<24} {_fmt_bytes(size):>10}  "
              f"CORRUPT: {e}")
        return {}
    verdict = "ok" if good == size else f"torn tail (+{size - good} B)"
    kind = "sealed" if sealed else "active"
    print(f"  {os.path.basename(path):<24} {_fmt_bytes(size):>10}  "
          f"gen={gen} base={base} records={count} [{kind}] {verdict}")
    return {"shard": shard, "gen": gen, "base": base, "count": count,
            "path": path, "size": size}


def iter_segment_records(path: str):
    """(offset, payload) for every whole record of one segment — a
    standalone read-only scan (ShardLog recovery would SEAL a live
    node's active file; a dump tool must never write)."""
    import zlib

    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _HDR.size:
        return
    _m, _v, _shard, _gen, base = _HDR.unpack_from(data, 0)
    off, rec_off = _HDR.size, base
    while off + _REC.size <= len(data):
        crc, ln = _REC.unpack_from(data, off)
        if ln > MAX_RECORD or off + _REC.size + ln > len(data):
            return
        payload = data[off + _REC.size:off + _REC.size + ln]
        if zlib.crc32(payload) != crc:
            return
        yield rec_off, payload
        off += _REC.size + ln
        rec_off += 1


def peek_records(path: str, n: int) -> None:
    """Decode the newest n records of one segment."""
    recs = list(iter_segment_records(path))[-n:]
    now_ms = int(datetime.datetime.now().timestamp() * 1e3)
    for off, payload in recs:
        try:
            d = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            print(f"    @{off}: undecodable record")
            continue
        age = (now_ms - d.get("ts", now_ms)) / 1e3
        print(f"    @{off}: topic={d.get('topic')!r} "
              f"qos={d.get('qos')} "
              f"payload={len(d.get('payload', ''))} B(b64) "
              f"age={age:,.1f}s")


def dump_shard(shard_dir: str, records: int) -> None:
    segs = sorted(
        os.path.join(shard_dir, f)
        for f in os.listdir(shard_dir)
        if f.startswith("seg.") and (f.endswith(".log")
                                     or f.endswith(".open"))
    )
    print(f"{os.path.basename(shard_dir)}:")
    if not segs:
        print("  (empty)")
        return
    infos = [i for i in (dump_segment(p) for p in segs) if i]
    if infos:
        total = sum(i["size"] for i in infos)
        lo = min(i["base"] for i in infos)
        hi = max(i["base"] + i["count"] for i in infos)
        print(f"  total {_fmt_bytes(total)}, offsets [{lo}, {hi})")
        if records and infos:
            newest = max(infos, key=lambda i: i["gen"])
            peek_records(newest["path"], records)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="ds dir (shard-<k>/ chains), one shard "
                                 "dir, or one segment file")
    ap.add_argument("--records", type=int, default=0, metavar="N",
                    help="decode the newest N records per shard")
    ns = ap.parse_args()
    if os.path.isfile(ns.path):
        dump_segment(ns.path)
        return 0
    if not os.path.isdir(ns.path):
        print(f"no such path: {ns.path}", file=sys.stderr)
        return 1
    shard_dirs = sorted(
        os.path.join(ns.path, f)
        for f in os.listdir(ns.path)
        if f.startswith("shard-")
        and os.path.isdir(os.path.join(ns.path, f))
    )
    if not shard_dirs:  # pointed straight at one shard dir
        shard_dirs = [ns.path]
    for d in shard_dirs:
        dump_shard(d, ns.records)
    return 0


if __name__ == "__main__":
    sys.exit(main())
