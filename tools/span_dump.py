"""Human-readable dump of the message-lifecycle span plane.

The span plane (`emqx_tpu/observe/spans.py`) head-samples publishes and
stamps a monotonic timestamp at every plane boundary — hooks, submit,
collect, enqueue, wire, the cross-node forward leg, the durable-log ds
leg.  This tool renders two views from a JSON export
(``SpanPlane.save(path)``, ``bench.py --spans --emit-stats``):

* the per-stage attribution table — count and bucket-derived
  p50/p99/p999 per stage ("where do messages spend their time");
* the slowest-K span waterfalls — the full stage-by-stage record of
  the tail messages the histograms can only hint at.

From Python, call :func:`dump` on a live plane::

    from emqx_tpu.observe import spans
    from tools.span_dump import dump
    print(dump(spans.plane().export()))

Usage:
    python tools/span_dump.py spans.json             # both views
    python tools/span_dump.py spans.json --slow 16   # more tail spans
    python tools/span_dump.py spans.json --recent    # recent ring too
    python tools/span_dump.py spans.json --json      # schema-pinned JSON
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from emqx_tpu.observe.spans import KNOWN_STAGES  # noqa: E402

SCHEMA = "emqx-tpu/span-dump/v1"


def _ms(v) -> str:
    return "-" if v is None else f"{v:.3f}"


def format_stages(export: dict) -> str:
    """The per-stage attribution table (declared-stage order)."""
    stages = export.get("stages") or {}
    lines = [
        f"spans: 1/{export.get('sample', '?')} sampled, "
        f"{export.get('started', 0)} started, "
        f"{export.get('completed', 0)} completed, "
        f"{export.get('remote_closed', 0)} remote forward legs",
        "",
        f"{'stage':<9} {'count':>8} {'p50 ms':>10} {'p99 ms':>10} "
        f"{'p999 ms':>10}",
    ]
    for stage in KNOWN_STAGES:
        row = stages.get(stage) or {}
        n = row.get("count", 0)
        lines.append(
            f"{stage:<9} {n:>8} "
            f"{_ms(row.get('p50') if n else None):>10} "
            f"{_ms(row.get('p99') if n else None):>10} "
            f"{_ms(row.get('p999') if n else None):>10}"
        )
    total = export.get("total_ms")
    if total:
        lines.append(
            f"{'total':<9} {export.get('completed', 0):>8} "
            f"{_ms(total.get('p50')):>10} {_ms(total.get('p99')):>10} "
            f"{_ms(total.get('p999')):>10}"
        )
    return "\n".join(lines)


def _span_line(rec: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(rec.get("ts", 0)))
    waterfall = " ".join(
        f"{stage}={rec['stages'][stage]:.3f}"
        for stage in KNOWN_STAGES if stage in (rec.get("stages") or {})
    )
    origin = f" [{rec['origin']}->{rec['node']}]" if rec.get("origin") \
        else ""
    return (
        f"{ts} {rec.get('total_ms', 0.0):>9.3f}ms "
        f"{rec.get('topic', '?'):<28}{origin} {waterfall}"
    )


def format_slowest(export: dict, k: int = 8) -> str:
    """Slowest-K span waterfalls, slowest first (per-stage ms)."""
    recs = (export.get("slowest") or [])[:k]
    if not recs:
        return "no completed spans recorded"
    return "\n".join(
        ["slowest spans (per-stage ms):"]
        + [f"  {_span_line(r)}" for r in recs]
    )


def format_recent(export: dict, k: int = 16) -> str:
    recs = (export.get("recent") or [])[-k:]
    if not recs:
        return "no recent spans"
    return "\n".join(
        ["recent spans (oldest first):"]
        + [f"  {_span_line(r)}" for r in recs]
    )


def dump(export: dict, slow: int = 8, recent: bool = False) -> str:
    out = [format_stages(export), "", format_slowest(export, slow)]
    if recent:
        out += ["", format_recent(export)]
    return "\n".join(out)


def to_json(export: dict) -> str:
    """Schema-pinned machine-readable re-emit: soak/CI jobs gate on
    stage p99s from this (`.stages.<stage>.p99`), so the field layout
    is a contract — a rename is a breaking change HERE, caught by the
    render test, not discovered in a downstream pipeline."""
    out = dict(export)
    out["schema"] = SCHEMA
    return json.dumps(out, indent=2, sort_keys=True)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="render a span-plane JSON export"
    )
    ap.add_argument("path", help="JSON file from SpanPlane.save / "
                                 "bench.py --spans --emit-stats")
    ap.add_argument("--slow", type=int, default=8,
                    help="tail spans to show (default 8)")
    ap.add_argument("--recent", action="store_true",
                    help="also print the recent-span ring")
    ap.add_argument("--json", action="store_true",
                    help="emit schema-pinned JSON instead of tables")
    ns = ap.parse_args()
    with open(ns.path, "r", encoding="utf-8") as f:
        export = json.load(f)
    # bench exports nest the plane dump under "spans"
    if "stages" not in export and "spans" in export:
        export = export["spans"]
    if ns.json:
        print(to_json(export))
    else:
        print(dump(export, slow=ns.slow, recent=ns.recent))


if __name__ == "__main__":
    main()
