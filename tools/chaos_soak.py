#!/usr/bin/env python
"""Chaos soak (`make chaos`): prove the self-healing data plane under a
seeded fault schedule, across multiple seeds.

Three phases per seed, all driven through the fault-injection plane
(`emqx_tpu/fault/`) so every run is reproducible from its seed:

1. cluster — a 3-node in-process cluster (real loopback sockets) takes
   a QoS1 publish stream through three weather fronts: clean, lossy
   (random send/forward drops), and a full partition (every inbound
   frame resets its connection).  Invariants: after heal, every QoS1
   message arrived at every remote subscriber EXACTLY once (spool +
   replay + receiver msgid dedup), and every spool drained.

2. engine — a hybrid TopicMatchEngine serves a fixed topic batch
   against a CPU-trie oracle while the device collect path is faulted
   into stalling.  Invariants: engine/oracle parity on every tick
   (faulted or not), the device breaker opens after consecutive
   timeouts (engine_device_degraded alarm raised), and with the fault
   lifted a completed probe closes it again (alarm cleared).

3. ckpt — snapshot store IO faults: an injected read failure on the
   newest snapshot must fall back to the older one; an injected write
   failure must surface as the exception the checkpoint manager alarms
   on.

4. ds — durable-message-log crash soak: a REAL child process appends a
   QoS1 stream through the write-behind buffer, recording (after each
   fsync'd flush) how far is committed; the parent `kill -9`s it
   mid-flush at a seeded random moment, recovers the log (torn-tail
   truncation), and resumes a parked session subscribed to the stream.
   Invariants: every committed message is replayed AT LEAST once, and
   receiver-side (mid) dedup makes delivery exactly-once.

5. repl — ds append replication (`make repl-soak`): a leader child
   streams appends while replicating to a follower child over a real
   PeerLink; the parent `kill -9`s the LEADER mid-flush in one
   sub-phase and the FOLLOWER mid-ack in the other, at seeded random
   moments.  Invariants: every record at/below the leader-recorded
   replicated watermark exists byte-identical in the follower's
   recovered mirror (zero loss <= watermark), the mirror is always a
   prefix of the leader's log (no invention, no reorder), replaying
   the mirror delivers exactly-once under mid dedup, and a follower
   kill never blocks the leader's append/flush path (progress keeps
   advancing while degraded).

Also asserts the disarmed plane is effectively free (sub-microsecond
per fault point) so it can stay compiled into the bench hot path.
"""

import argparse
import asyncio
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from emqx_tpu import fault  # noqa: E402
from emqx_tpu.broker.message import Message  # noqa: E402
from emqx_tpu.broker.packet import SubOpts  # noqa: E402
from emqx_tpu.broker.session import Session  # noqa: E402
from emqx_tpu.checkpoint.store import SnapshotStore  # noqa: E402
from emqx_tpu.cluster.node import ClusterBroker, ClusterNode  # noqa: E402
from emqx_tpu.models.engine import TopicMatchEngine  # noqa: E402
from emqx_tpu.models.reference import CpuTrieIndex  # noqa: E402
from emqx_tpu.node import poll_health_alarms  # noqa: E402
from emqx_tpu.observe.alarm import AlarmManager  # noqa: E402


class SoakFailure(AssertionError):
    pass


def check(cond, msg):
    if not cond:
        raise SoakFailure(msg)


# --------------------------------------------------------------- cluster

class Sink:
    """Minimal channel: records deliveries (ChannelLike protocol)."""

    def __init__(self, clientid, session):
        self.clientid = clientid
        self.session = session
        self.got = []

    def deliver(self, items):
        self.got.extend(items)

    def kick(self, reason_code=0):
        pass


def attach(node, clientid, filt, qos=1):
    s = Session(clientid=clientid)
    s.subscriptions[filt] = SubOpts(qos=qos)
    sink = Sink(clientid, s)
    node.broker.cm.register_channel(sink)
    node.broker.subscribe(clientid, filt, SubOpts(qos=qos))
    return sink


async def wait_until(pred, timeout=30.0, ivl=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise SoakFailure(f"timeout waiting for {what}")
        await asyncio.sleep(ivl)


async def cluster_phase(seed: int, verbose: bool) -> dict:
    nodes = []
    for i in range(3):
        b = ClusterBroker()
        node = ClusterNode(
            f"c{i}", b,
            heartbeat_ivl=0.2, miss_limit=2,
            route_hold=60.0,  # faults are transient: routes must survive
            reconnect_ivl=0.1, reconnect_max=1.0,
        )
        node.replay_timeout = 0.8  # fast retry loop under lossy faults
        await node.start()
        nodes.append(node)
    stats = {}
    try:
        for a in nodes:
            for b in nodes:
                if a is not b:
                    a.join(b.name, ("127.0.0.1", b.transport.port))
        await wait_until(
            lambda: all(len(x.up_peers()) == 2 for x in nodes),
            timeout=20, what="mesh formation",
        )
        n0 = nodes[0]
        sinks = [attach(x, f"s{i}", "chaos/#", qos=1)
                 for i, x in enumerate(nodes[1:], start=1)]
        await wait_until(
            lambda: all(
                "chaos/#" in n0.remote.filters_of(x.name)
                for x in nodes[1:]
            ),
            timeout=20, what="route replication",
        )

        published = []

        def publish(n, tag):
            for i in range(n):
                payload = f"{tag}-{i}".encode()
                n0.broker.publish(
                    Message(topic="chaos/t", payload=payload, qos=1)
                )
                published.append(payload)

        # front 1: clean weather
        publish(30, "clean")
        await wait_until(
            lambda: all(len(s.got) >= 30 for s in sinks),
            timeout=20, what="clean-wave delivery",
        )

        # front 2: lossy link — random frame + forward-batch drops
        fault.configure({
            "transport.send": {"action": "drop", "p": 0.4},
            "cluster.forward": {"action": "drop", "p": 0.25},
        }, seed=seed)
        for _ in range(6):
            publish(10, "lossy")
            await asyncio.sleep(0.25)

        # front 3: full partition — every inbound frame resets its
        # connection, links flap down, heartbeats miss
        fault.configure({
            "transport.recv": {"action": "error", "p": 1.0},
        }, seed=seed)
        await wait_until(
            lambda: all(
                n0._status.get(x.name) == "down" for x in nodes[1:]
            ),
            timeout=20, what="partition detection",
        )
        publish(30, "part")

        # heal and drain
        fault.reset()
        await wait_until(
            lambda: all(len(x.up_peers()) == 2 for x in nodes),
            timeout=30, what="mesh re-formation after heal",
        )
        await wait_until(
            lambda: all(x.spool_pending() == 0 for x in nodes)
            and not any(x._replay_tasks for x in nodes),
            timeout=60, what="forward spool drain",
        )
        await wait_until(
            lambda: all(len(s.got) >= len(published) for s in sinks),
            timeout=30, what="post-heal delivery",
        )
        await asyncio.sleep(1.0)  # settle: catch straggler duplicates

        want = sorted(published)
        for i, s in enumerate(sinks):
            got = sorted(m.payload for _f, m in s.got)
            check(
                got == want,
                f"seed {seed}: sink {i} delivery mismatch — "
                f"{len(got)} got vs {len(want)} published "
                f"(missing={len(set(want) - set(got))}, "
                f"dupes={len(got) - len(set(got))})",
            )
        check(
            all(x.spool_dropped == 0 for x in nodes),
            f"seed {seed}: spool overflow dropped records",
        )
        stats = {
            "published": len(published),
            "spooled": n0.broker.metrics.get("messages.forward.spooled"),
            "replayed": n0.broker.metrics.get("messages.forward.replayed"),
            "dup_dropped": sum(
                x.broker.metrics.get("messages.forward.dup_dropped")
                for x in nodes
            ),
        }
        if verbose:
            print(f"  cluster: {stats}")
        return stats
    finally:
        fault.reset()
        for x in nodes:
            await x.stop()


# ---------------------------------------------------------------- engine

def engine_phase(seed: int, verbose: bool) -> dict:
    eng = TopicMatchEngine(min_batch=8)
    filters = [f"s/{i}/+" for i in range(40)] + ["chaos/#", "deep/a/b/c"]
    fids = eng.add_filters(filters)
    oracle = CpuTrieIndex()
    for f, fid in zip(filters, fids):
        oracle.insert(f, fid)
    topics = [f"s/{i}/x" for i in range(20)] + [
        "chaos/t", "deep/a/b/c", "none/q",
    ]
    want = [oracle.match(t) for t in topics]
    alarms = AlarmManager(node="soak")

    def tick():
        got = eng.match(topics)
        check(got == want, f"seed {seed}: engine/oracle parity broken")
        poll_health_alarms(eng, None, alarms)

    if eng._reg is None:
        # no native lib: the hybrid host path cannot serve, so exercise
        # the breaker state machine + alarm lifecycle directly
        for _ in range(eng.breaker_threshold):
            eng._note_dev_timeout()
        poll_health_alarms(eng, None, alarms)
        check(eng.breaker_open, "breaker did not open")
        check(alarms.is_active("engine_device_degraded"),
              "degraded alarm not raised")
        tick()
        eng._note_dev_ok()
        poll_health_alarms(eng, None, alarms)
        check(not eng.breaker_open, "breaker did not close")
        check(not alarms.is_active("engine_device_degraded"),
              "degraded alarm not cleared")
        return {"mode": "state-machine"}

    eng.hybrid = True
    eng.probe_interval = 1000.0  # no host-refresh flips during the trip
    tick()  # host serves (unmeasured); warms the device via the probe
    # force the arbiter device-side, then stall every collect: each tick
    # times out, decays rate_dev 4x, and counts one consecutive timeout
    eng.rate_host, eng.rate_dev = 1.0, 1e9
    eng._last_host_meas = time.monotonic()
    fault.configure({
        "engine.collect": {"action": "drop"},
        "engine.probe": {"action": "drop"},
    }, seed=seed)
    trip_ticks = 0
    for _ in range(30):
        tick()
        trip_ticks += 1
        if eng.breaker_open:
            break
    check(eng.breaker_open,
          f"seed {seed}: breaker never opened ({trip_ticks} ticks)")
    check(alarms.is_active("engine_device_degraded"),
          f"seed {seed}: engine_device_degraded not raised")
    # host-only serving while open; probes may dispatch but never harvest
    eng.probe_interval = 0.0
    for _ in range(5):
        tick()
    check(eng.breaker_open, f"seed {seed}: breaker flapped while faulted")

    # heal: the pending (or next) probe completes and closes the breaker
    fault.reset()
    deadline = time.monotonic() + 30
    while eng.breaker_open and time.monotonic() < deadline:
        tick()
        time.sleep(0.01)
    check(not eng.breaker_open, f"seed {seed}: breaker never re-closed")
    poll_health_alarms(eng, None, alarms)
    check(not alarms.is_active("engine_device_degraded"),
          f"seed {seed}: engine_device_degraded not cleared")
    out = {
        "mode": "hybrid",
        "trip_ticks": trip_ticks,
        "dev_timeouts": eng.dev_timeout_count,
        "breaker_trips": eng.breaker_trips,
    }
    if verbose:
        print(f"  engine: {out}")
    return out


# ------------------------------------------------------------------ ckpt

def ckpt_phase(seed: int, verbose: bool) -> dict:
    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as d:
        store = SnapshotStore(d, keep=3)
        store.save({"a": np.arange(8)}, {"gen": 1})
        store.save({"a": np.arange(8) * 2}, {"gen": 2})
        # newest snapshot read fails once: restore must fall back
        fault.configure(
            {"ckpt.read": {"action": "error", "times": 1}}, seed=seed
        )
        try:
            loaded = store.load_newest()
            check(loaded is not None, "no snapshot survived the fault")
            _arr, meta, _path = loaded
            check(meta["gen"] == 1,
                  f"seed {seed}: fallback loaded gen {meta['gen']}, want 1")
            check(store.fallbacks == 1, "fallback not counted")
            # write faults surface as the exception the manager alarms on
            fault.configure(
                {"ckpt.write": {"action": "error"}}, seed=seed
            )
            try:
                store.save({"a": np.arange(4)}, {"gen": 3})
            except OSError:
                pass
            else:
                raise SoakFailure("faulted ckpt write did not raise")
        finally:
            fault.reset()
    if verbose:
        print("  ckpt: fallback + write-failure ok")
    return {"fallbacks": 1}


# -------------------------------------------------------------------- ds

def _ds_config(shards: int = 2):
    from emqx_tpu.config.config import Config

    return Config({"ds": {
        "enable": True,
        "shards": shards,
        "flush_bytes": 512,  # small watermark: many flush boundaries
        "seg_bytes": 4096,   # frequent segment rolls under the stream
    }})


def ds_child(directory: str) -> None:
    """Child half of the ds front: append a numbered QoS1 stream,
    flushing every few messages and recording the committed count
    AFTER each flush returns (so `progress` is always <= what the
    fsync made durable).  Runs until SIGKILLed by the parent."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.ds.manager import DsManager

    mgr = DsManager(Broker(), os.path.join(directory, "ds"), _ds_config())
    prog = os.path.join(directory, "progress")
    for i in range(200_000):  # bounded: can't run away if orphaned
        mgr.append(Message(
            topic=f"soak/ds/{i % 5}", payload=str(i).encode(), qos=1
        ))
        if (i + 1) % 7 == 0:
            mgr.flush_all()
            tmp = prog + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(i + 1))
            os.replace(tmp, prog)


def ds_phase(seed: int, verbose: bool) -> dict:
    rng = random.Random(f"ds:{seed}")
    d = tempfile.mkdtemp(prefix="chaos_ds_")
    proc = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--ds-child", d],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        prog = os.path.join(d, "progress")
        deadline = time.monotonic() + 30
        while not os.path.exists(prog):
            if proc.poll() is not None:
                err = proc.stderr.read().decode(errors="replace")
                raise SoakFailure(f"ds child died before flushing: {err}")
            if time.monotonic() > deadline:
                raise SoakFailure("ds child never flushed")
            time.sleep(0.01)
        # let the stream run, then kill -9 at a seeded random moment —
        # mid-append, mid-flush, mid-roll, whatever is in flight
        time.sleep(rng.uniform(0.05, 0.8))
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
        with open(prog) as f:
            committed = int(f.read())

        # recovery: reopen the log (torn-tail truncation + re-seal),
        # resume a parked session subscribed to the whole stream
        from emqx_tpu.broker.broker import Broker
        from emqx_tpu.ds.manager import DsManager

        b = Broker()
        mgr = DsManager(b, os.path.join(d, "ds"), _ds_config())
        try:
            session = Session(
                clientid="soaker", expiry_interval=300, max_mqueue=0
            )
            session.subscriptions["soak/ds/#"] = SubOpts(qos=1)
            session.ds_cursor = {
                k: (0, 0) for k in range(mgr.n_shards)
            }
            n, gap = mgr.replay_into(session)
            check(gap == 0, f"seed {seed}: unexpected GC gap {gap}")
            # receiver-side (mid) dedup: at-least-once -> exactly-once
            seen_mids, seqs = set(), []
            for m in session.mqueue.peek_all():
                if m.mid in seen_mids:
                    continue
                seen_mids.add(m.mid)
                seqs.append(int(m.payload))
            missing = set(range(committed)) - set(seqs)
            check(
                not missing,
                f"seed {seed}: committed messages lost after kill -9 "
                f"(flushed {committed}, missing {sorted(missing)[:5]})",
            )
            check(
                len(seqs) == len(set(seqs)),
                f"seed {seed}: duplicate seqs after mid dedup",
            )
            out = {
                "committed": committed,
                "replayed": n,
                "delivered": len(seqs),
                "uncommitted_recovered": len(seqs) - committed,
            }
            if verbose:
                print(f"  ds: {out}")
            return out
        finally:
            mgr.close()
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        import shutil

        shutil.rmtree(d, ignore_errors=True)


# ------------------------------------------------------------------ repl

def _repl_config(shards: int = 2):
    from emqx_tpu.config.config import Config

    return Config({"ds": {
        "enable": True,
        "shards": shards,
        "flush_bytes": 512,   # many flush (= ship) boundaries
        "seg_bytes": 4096,    # frequent segment rolls under the stream
        "repl.enable": True,
        "repl.ack_timeout": 1.0,
        "repl.retry_interval": 0.1,
    }})


def repl_follower_child(directory: str) -> None:
    """Follower half of the repl front: a cluster node with a
    DsReplicator mirroring whatever a leader ships at it.  Publishes
    its transport port, then idles until SIGKILLed mid-ack."""
    from emqx_tpu.ds.manager import DsManager
    from emqx_tpu.ds.repl import DsReplicator

    async def run() -> None:
        b = ClusterBroker()
        conf = _repl_config()
        ds = DsManager(b, os.path.join(directory, "follower-ds"), conf,
                       metrics=b.metrics)
        b.ds = ds
        node = ClusterNode("repl-f", b, heartbeat_ivl=0.2)
        repl = DsReplicator(node, ds, conf)
        await node.start()
        repl.start()
        port_file = os.path.join(directory, "follower-port")
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(node.transport.port))
        os.replace(tmp, port_file)
        await asyncio.sleep(3600)  # until SIGKILL

    asyncio.run(run())


def repl_leader_child(directory: str) -> None:
    """Leader half: joins the follower, appends a numbered QoS1 stream
    with explicit flushes (each flush hands the range to the
    replicator), and records — AFTER each flush returns — the appended
    count plus a watermark snapshot.  The recorded watermark is always
    <= what the follower has fsync'd and acked, so it is the loss
    floor the parent verifies against the recovered mirror."""
    import json as _json

    from emqx_tpu.ds.manager import DsManager
    from emqx_tpu.ds.repl import DsReplicator

    async def run() -> None:
        with open(os.path.join(directory, "follower-port")) as f:
            port = int(f.read())
        b = ClusterBroker()
        conf = _repl_config()
        ds = DsManager(b, os.path.join(directory, "leader-ds"), conf,
                       metrics=b.metrics)
        b.ds = ds
        node = ClusterNode("repl-l", b, heartbeat_ivl=0.2)
        repl = DsReplicator(node, ds, conf)
        await node.start()
        repl.start()
        node.join("repl-f", ("127.0.0.1", port))
        deadline = time.monotonic() + 20
        while "repl-f" not in node.up_peers():
            if time.monotonic() > deadline:
                raise RuntimeError("leader never saw the follower up")
            await asyncio.sleep(0.01)
        prog = os.path.join(directory, "progress")
        for i in range(200_000):  # bounded: can't run away if orphaned
            ds.append(Message(
                topic=f"soak/repl/{i % 5}", payload=str(i).encode(),
                qos=1,
            ))
            await asyncio.sleep(0)  # let the drain task ship
            if (i + 1) % 7 == 0:
                ds.flush_all()
                await asyncio.sleep(0.002)  # acks land, watermark moves
                state = {
                    "appended": i + 1,
                    "watermark": {str(k): v
                                  for k, v in repl.watermark.items()},
                }
                tmp = prog + ".tmp"
                with open(tmp, "w") as f:
                    _json.dump(state, f)
                os.replace(tmp, prog)

    asyncio.run(run())


def _read_progress(path: str) -> dict:
    import json as _json

    with open(path) as f:
        return _json.load(f)


def repl_phase(seed: int, verbose: bool) -> dict:
    """Both kill targets per seed: leader mid-flush, follower mid-ack."""
    import json as _json
    import shutil

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.persist import message_from_dict
    from emqx_tpu.ds.log import ShardLog
    from emqx_tpu.ds.manager import DsManager

    out = {}
    for victim in ("leader", "follower"):
        rng = random.Random(f"repl:{seed}:{victim}")
        d = tempfile.mkdtemp(prefix="chaos_repl_")
        fproc = lproc = None
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            me = os.path.abspath(__file__)
            fproc = subprocess.Popen(
                [sys.executable, me, "--repl-follower", d], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            )
            port_file = os.path.join(d, "follower-port")
            deadline = time.monotonic() + 30
            while not os.path.exists(port_file):
                if fproc.poll() is not None:
                    err = fproc.stderr.read().decode(errors="replace")
                    raise SoakFailure(f"repl follower died early: {err}")
                if time.monotonic() > deadline:
                    raise SoakFailure("repl follower never listened")
                time.sleep(0.01)
            lproc = subprocess.Popen(
                [sys.executable, me, "--repl-leader", d], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            )
            prog = os.path.join(d, "progress")
            deadline = time.monotonic() + 30
            while not os.path.exists(prog):
                if lproc.poll() is not None:
                    err = lproc.stderr.read().decode(errors="replace")
                    raise SoakFailure(f"repl leader died early: {err}")
                if time.monotonic() > deadline:
                    raise SoakFailure("repl leader never flushed")
                time.sleep(0.01)
            time.sleep(rng.uniform(0.05, 0.8))
            if victim == "leader":
                os.kill(lproc.pid, signal.SIGKILL)
                lproc.wait()
                os.kill(fproc.pid, signal.SIGKILL)
                fproc.wait()
            else:
                os.kill(fproc.pid, signal.SIGKILL)
                fproc.wait()
                # the leader's flush path must NOT block on the dead
                # follower hop: appends keep committing while degraded
                before = _read_progress(prog)["appended"]
                deadline = time.monotonic() + 15
                while _read_progress(prog)["appended"] <= before:
                    if time.monotonic() > deadline:
                        raise SoakFailure(
                            f"seed {seed}: leader stopped flushing "
                            f"after follower kill (blocked at {before})"
                        )
                    time.sleep(0.05)
                os.kill(lproc.pid, signal.SIGKILL)
                lproc.wait()
            state = _read_progress(prog)
            committed = int(state["appended"])
            wm = {int(k): int(v)
                  for k, v in state.get("watermark", {}).items()}

            n_shards = 2
            mirror_root = os.path.join(
                d, "follower-ds", "mirror", "repl-l")
            leader_seqs_below_wm = set()
            all_leader_seqs = set()
            mirror_records = 0
            for k in range(n_shards):
                llog = ShardLog(
                    os.path.join(d, "leader-ds", f"shard-{k}"), k)
                lrecs, _n, lgap = llog.read_from(0, 10 ** 6)
                llog.close()
                check(lgap == 0,
                      f"seed {seed}/{victim}: leader log gap {lgap}")
                for o, p in lrecs:
                    seq = int(message_from_dict(
                        _json.loads(p.decode())).payload)
                    all_leader_seqs.add(seq)
                    if o < wm.get(k, 0):
                        leader_seqs_below_wm.add(seq)
                mpath = os.path.join(mirror_root, f"shard-{k}")
                if not os.path.isdir(mpath):
                    check(wm.get(k, 0) == 0,
                          f"seed {seed}/{victim}: watermark {wm.get(k)}"
                          f" on shard {k} but no mirror on disk")
                    continue
                mlog = ShardLog(mpath, k)
                mrecs, _n, mgap = mlog.read_from(0, 10 ** 6)
                mlog.close()
                check(mgap == 0,
                      f"seed {seed}/{victim}: mirror gap {mgap}")
                mirror_records += len(mrecs)
                # zero loss at/below the watermark, and the mirror is
                # a byte-identical prefix of the leader's log — the
                # acked fsync ordering means a kill at ANY moment on
                # either side cannot break these
                check(
                    len(mrecs) >= wm.get(k, 0),
                    f"seed {seed}/{victim}: shard {k} mirror ends at "
                    f"{len(mrecs)} < watermark {wm.get(k, 0)}",
                )
                check(
                    mrecs == lrecs[:len(mrecs)],
                    f"seed {seed}/{victim}: shard {k} mirror diverges "
                    f"from the leader log",
                )
            check(
                all_leader_seqs >= set(range(committed)),
                f"seed {seed}/{victim}: leader lost committed records "
                f"({sorted(set(range(committed)) - all_leader_seqs)[:5]})",
            )

            # exactly-once: a DsManager pointed at the recovered mirror
            # (same dir/shard-<k> layout) replays a parked session —
            # mid dedup turns at-least-once into exactly-once
            mgr = DsManager(Broker(), mirror_root, _repl_config())
            try:
                session = Session(
                    clientid="repl-soaker", expiry_interval=300,
                    max_mqueue=0,
                )
                session.subscriptions["soak/repl/#"] = SubOpts(qos=1)
                session.ds_cursor = {
                    k: (0, 0) for k in range(mgr.n_shards)
                }
                mgr.replay_into(session)
                seen_mids, seqs = set(), []
                for m in session.mqueue.peek_all():
                    if m.mid in seen_mids:
                        continue
                    seen_mids.add(m.mid)
                    seqs.append(int(m.payload))
                check(
                    len(seqs) == len(set(seqs)),
                    f"seed {seed}/{victim}: duplicate seqs out of the "
                    f"mirror replay after mid dedup",
                )
                missing = leader_seqs_below_wm - set(seqs)
                check(
                    not missing,
                    f"seed {seed}/{victim}: watermark-covered messages "
                    f"lost (missing {sorted(missing)[:5]})",
                )
            finally:
                mgr.close()
            out[victim] = {
                "committed": committed,
                "watermark": sum(wm.values()),
                "mirrored": mirror_records,
            }
            if verbose:
                print(f"  repl/{victim}: {out[victim]}")
        finally:
            for proc in (fproc, lproc):
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()
            shutil.rmtree(d, ignore_errors=True)
    return out


# -------------------------------------------------------------- overhead

def overhead_check() -> float:
    """Disarmed plane cost per fault point (must stay ~free)."""
    fault.reset()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        fault.inject("engine.collect", err=False)
    per_call = (time.perf_counter() - t0) / n
    check(per_call < 5e-6,
          f"disarmed fault point costs {per_call * 1e9:.0f} ns (> 5 us)")
    return per_call


FRONTS = ("cluster", "engine", "ckpt", "ds", "repl")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=5,
                    help="number of seeds to soak (1..N)")
    ap.add_argument("--fronts", default=",".join(FRONTS),
                    help="comma list of fronts to run "
                         f"(default: {','.join(FRONTS)})")
    ap.add_argument("--ds-child", default=None, metavar="DIR",
                    help=argparse.SUPPRESS)  # internal: ds-front child
    ap.add_argument("--repl-leader", default=None, metavar="DIR",
                    help=argparse.SUPPRESS)  # internal: repl-front child
    ap.add_argument("--repl-follower", default=None, metavar="DIR",
                    help=argparse.SUPPRESS)  # internal: repl-front child
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    if args.ds_child:
        ds_child(args.ds_child)
        return 0
    if args.repl_leader:
        repl_leader_child(args.repl_leader)
        return 0
    if args.repl_follower:
        repl_follower_child(args.repl_follower)
        return 0
    fronts = [f.strip() for f in args.fronts.split(",") if f.strip()]
    unknown = set(fronts) - set(FRONTS)
    if unknown:
        print(f"unknown front(s): {sorted(unknown)}", file=sys.stderr)
        return 2

    per_call = overhead_check()
    print(f"disarmed fault point: {per_call * 1e9:.0f} ns/call")

    failures = 0
    for seed in range(1, args.seeds + 1):
        t0 = time.monotonic()
        cs = es = dss = rps = {}
        try:
            if "cluster" in fronts:
                cs = asyncio.run(cluster_phase(seed, args.verbose))
            if "engine" in fronts:
                es = engine_phase(seed, args.verbose)
            if "ckpt" in fronts:
                ckpt_phase(seed, args.verbose)
            if "ds" in fronts:
                dss = ds_phase(seed, args.verbose)
            if "repl" in fronts:
                rps = repl_phase(seed, args.verbose)
        except SoakFailure as e:
            failures += 1
            print(f"seed {seed}: FAIL — {e}")
            fault.reset()
            continue
        finally:
            fault.reset()
        dt = time.monotonic() - t0
        print(
            f"seed {seed}: ok in {dt:.1f}s — "
            f"{cs.get('published', 0)} msgs "
            f"(spooled {cs.get('spooled', 0)}, "
            f"replayed {cs.get('replayed', 0)}, "
            f"dedup {cs.get('dup_dropped', 0)}), "
            f"engine {es.get('mode', '-')} "
            f"(timeouts {es.get('dev_timeouts', 0)}, "
            f"trips {es.get('breaker_trips', 0)}), "
            f"ds kill-9 (committed {dss.get('committed', 0)}, "
            f"delivered {dss.get('delivered', 0)}), "
            f"repl kill-9 (wm {rps.get('leader', {}).get('watermark', 0)}"
            f"/{rps.get('follower', {}).get('watermark', 0)})"
        )
    if failures:
        print(f"{failures} seed(s) FAILED")
        return 1
    print(f"all {args.seeds} seeds passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
