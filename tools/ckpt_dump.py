"""Inspect an engine checkpoint directory (snapshots + churn WAL).

Usage:
    python tools/ckpt_dump.py <ckpt-dir>          # <data_dir>/ckpt
    python tools/ckpt_dump.py <file.ckpt>         # one snapshot file
    python tools/ckpt_dump.py <ckpt-dir> --wal 5  # decode 5 WAL records

Prints, per snapshot (newest first): seq, size, frame verdict
(ok/corrupt), the meta block (kind, filter count, WAL watermark, wall
time), per-shard table occupancy, and the largest arrays by size.  For
the WAL: record/byte backlog and a peek at the oldest records.  Reads
only — safe against a live node's directory (snapshots are immutable
once renamed in; the WAL peek uses the same torn-tail-tolerant reader
as recovery).
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from emqx_tpu.checkpoint.store import SnapshotStore, SnapshotError  # noqa: E402
from emqx_tpu.checkpoint.wal import unpack_ops  # noqa: E402
from emqx_tpu.utils.replayq import ReplayQ  # noqa: E402


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n} B"


def dump_snapshot(path: str, top: int = 8) -> None:
    size = os.path.getsize(path)
    try:
        arrays, meta = SnapshotStore.load_file(path)
    except SnapshotError as e:
        print(f"{os.path.basename(path)}  {_fmt_bytes(size)}  CORRUPT: {e}")
        return
    wall = meta.get("wall_time")
    when = (
        datetime.datetime.fromtimestamp(wall).isoformat(timespec="seconds")
        if wall else "?"
    )
    print(f"{os.path.basename(path)}  {_fmt_bytes(size)}  ok")
    print(f"  kind={meta.get('kind')}  filters={meta.get('n_filters')}  "
          f"wal_seq={meta.get('wal_seq')}  next_fid={meta.get('next_fid')}  "
          f"taken={when}")
    if meta.get("kind") == "engine":
        t = meta.get("tables", {})
        print(f"  tables: n_entries={t.get('n_entries'):,} "
              f"log2cap={t.get('log2cap')} desc_cap={t.get('desc_cap')} "
              f"max_levels={t.get('max_levels')}")
    elif meta.get("kind") == "sharded":
        occ = [s.get("n_entries", 0) for s in meta.get("shards", [])]
        print(f"  shards: {len(occ)} x log2cap="
              f"{[s.get('log2cap') for s in meta.get('shards', [])][:1]}"
              f" entries={occ} (total {sum(occ):,})")
    if meta.get("retained") is not None:
        print(f"  retained index: cap={meta['retained'].get('cap')}")
    by_size = sorted(arrays.items(), key=lambda kv: -kv[1].nbytes)[:top]
    for name, arr in by_size:
        print(f"    {name:<16} {str(arr.dtype):<8} {str(arr.shape):<18} "
              f"{_fmt_bytes(arr.nbytes)}")


def dump_wal(wal_dir: str, peek: int = 3) -> None:
    if not os.path.isdir(wal_dir):
        print("wal: (no directory)")
        return
    q = ReplayQ(wal_dir)
    try:
        print(f"wal: {q.pending_count():,} record(s) pending, "
              f"{_fmt_bytes(q.pending_bytes())} on disk, "
              f"acked through seq {q._acked}")
        shown = 0
        while shown < peek:
            _ref, items = q.pop(1)
            if not items:
                break
            try:
                adds, removes = unpack_ops(items[0])
                print(f"  record: +{len(adds)} -{len(removes)}"
                      + (f"  (e.g. +{adds[0]!r})" if adds else "")
                      + (f" (-{removes[0]!r})" if removes else ""))
            except (ValueError, UnicodeDecodeError) as e:
                print(f"  record: undecodable ({e})")
            shown += 1
    finally:
        q.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="checkpoint dir (snap/ + wal/) or one "
                                 ".ckpt file")
    ap.add_argument("--wal", type=int, default=3, metavar="N",
                    help="WAL records to peek at (default 3)")
    ap.add_argument("--top", type=int, default=8, metavar="N",
                    help="largest arrays to list per snapshot")
    ns = ap.parse_args()
    if os.path.isfile(ns.path):
        dump_snapshot(ns.path, top=ns.top)
        return 0
    snap_dir = os.path.join(ns.path, "snap")
    if not os.path.isdir(snap_dir):
        snap_dir = ns.path  # maybe pointed straight at snap/
    store = SnapshotStore(snap_dir)
    snaps = store.list()
    if not snaps:
        print(f"no snapshots under {snap_dir}")
    for _seq, path in snaps:
        dump_snapshot(path, top=ns.top)
    dump_wal(os.path.join(ns.path, "wal"), peek=ns.wal)
    return 0


if __name__ == "__main__":
    sys.exit(main())
