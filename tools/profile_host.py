"""Micro-profile the fused native host match path (the hybrid data plane).

Breaks an `engine.match()` host-path tick into measured phases so probe
optimization work targets the real bucket:

  pack    — Python str batch -> packed utf-8 (buf, offs)
  native  — etpu_match_host_verified (split+hash+probe+verify in C++)
  post    — numpy mask/cumsum + per-topic list assembly
  full    — engine.match_submit/match_collect_raw end-to-end

Run:  python tools/profile_host.py [--config N] [--ticks 512,1024,4096]
No device needed: the host path is host silicon by design.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build(config: int, subs_cap=None):
    import random

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "."))
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "benchmod", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    rng = random.Random(1234 + config)
    if config == 1:
        return bench.pop_exact_1k(rng)
    if config == 2:
        return bench.pop_wild_100k(rng)
    if config == 3:
        return bench.pop_mixed(rng, subs_cap or 1_000_000)
    if config == 4:
        return bench.pop_zipf(rng, subs_cap or 10_000_000)
    if config == 5:
        return bench.pop_mixed(rng, subs_cap or 10_000_000)
    raise SystemExit(f"unknown config {config}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=2)
    ap.add_argument("--subs", type=int, default=None)
    ap.add_argument("--ticks", default="512,1024,2048,4096")
    ap.add_argument("--iters", type=int, default=50)
    ns = ap.parse_args()

    import gc

    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.ops import native
    from emqx_tpu.ops.tables import PROBE

    filters, topics_fn = build(ns.config, ns.subs)
    # mirror bench.py's node-runtime GC tuning so p99 reflects the match
    # path, not young-gen sweeps over the resident population
    gc.collect()
    gc.freeze()
    _g0, _g1, _g2 = gc.get_threshold()
    gc.set_threshold(50_000, _g1, _g2)
    print(f"config {ns.config}: {len(filters):,} filters", file=sys.stderr)
    eng = TopicMatchEngine()
    t0 = time.time()
    eng.add_filters(filters)
    print(f"insert: {len(filters)/(time.time()-t0):,.0f}/s", file=sys.stderr)
    # host-only serving: hybrid on, device probes disabled
    eng.hybrid = True
    eng.rate_dev = 1.0
    eng.probe_interval = 1e9
    eng._last_dev_meas = time.monotonic() + 1e9

    t = eng.tables
    print(f"shapes live: {int(t.valid.sum())}, log2cap {t.log2cap}, "
          f"entries {t.n_entries:,}", file=sys.stderr)

    for tick in (int(x) for x in ns.ticks.split(",")):
        batches = [topics_fn() for _ in range(8)]
        batches = [(b * ((tick // len(b)) + 1))[:tick] for b in batches]

        # phase timings
        snap = eng._snapshot()
        (key_a, key_b, val, log2cap, incl, k_a, k_b,
         min_len, max_len, wild_root, valid) = snap
        vcap = int(valid.sum())
        pack_s = nat_s = post_s = 0.0
        for i in range(ns.iters):
            topics = batches[i % 8]
            p0 = time.perf_counter()
            tbuf, toffs = native.pack_strs(topics)
            p1 = time.perf_counter()
            res = native.match_host_verified(
                eng._reg, tbuf, toffs, len(topics), eng.space,
                key_a, key_b, val, log2cap, PROBE,
                incl, k_a, k_b, min_len, max_len, wild_root, valid, vcap,
            )
            p2 = time.perf_counter()
            fids, counts, colls = res
            n = len(topics)
            fid_list = fids.tolist()
            offs = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(counts, out=offs[1:])
            ol = offs.tolist()
            out = [fid_list[ol[k]:ol[k + 1]] for k in range(n)]
            p3 = time.perf_counter()
            pack_s += p1 - p0
            nat_s += p2 - p1
            post_s += p3 - p2

        # full path (submit+collect) latency distribution
        lat = []
        for i in range(ns.iters):
            b0 = time.perf_counter()
            eng.match_collect_raw(eng.match_submit(batches[i % 8]))
            lat.append(time.perf_counter() - b0)
        lat_ms = np.array(lat) * 1e3
        total = pack_s + nat_s + post_s
        per = ns.iters * tick
        print(
            f"tick {tick:5d}: pack {pack_s/ns.iters*1e3:7.3f} ms  "
            f"native {nat_s/ns.iters*1e3:7.3f} ms  "
            f"post {post_s/ns.iters*1e3:7.3f} ms  | "
            f"phases {per/total:,.0f}/s  full p50 "
            f"{np.percentile(lat_ms, 50):.3f} p99 "
            f"{np.percentile(lat_ms, 99):.3f} ms  "
            f"full {per/ sum(lat):,.0f}/s"
        )


if __name__ == "__main__":
    main()
