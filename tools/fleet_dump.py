"""Fleet-wide observability dump: the hub+workers shm topology view.

`tools/span_dump.py` renders ONE process's span plane; this tool
renders the whole fleet from a `WireSupervisor.fleet_export()` JSON
(schema `emqx-tpu/fleet-dump/v1`, also written by
``bench.py --spans-shm --emit-stats``):

* the fleet stage table — per-stage count/p50/p99 for every worker
  side by side, plus the merged fleet column (histograms merged
  bucket-by-bucket, `LatencyHistogram.merge`), so a one-worker tail is
  distinguishable from a fleet-wide one;
* per-lane ring health — submit/result ring occupancy, queued churn
  acks and live filter refcounts per shm lane, plus the hub's
  drain-cycle / fusion-group telemetry;
* cross-process span waterfalls — each worker's slowest-K spans tagged
  with the worker that recorded them.

From Python::

    from tools.fleet_dump import dump
    print(dump(supervisor.fleet_export()))

Usage:
    python tools/fleet_dump.py fleet.json            # all views
    python tools/fleet_dump.py fleet.json --slow 16  # more tail spans
    python tools/fleet_dump.py fleet.json --json     # schema-pinned JSON
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from emqx_tpu.observe.flight import LatencyHistogram  # noqa: E402
from emqx_tpu.observe.spans import KNOWN_STAGES  # noqa: E402

SCHEMA = "emqx-tpu/fleet-dump/v1"


def _hist(d: Optional[Dict]) -> Optional[LatencyHistogram]:
    if not d:
        return None
    try:
        return LatencyHistogram.from_dict(d)
    except (TypeError, ValueError):
        return None


def _cell(h: Optional[LatencyHistogram]) -> str:
    if h is None or not h.count:
        return f"{'-':>18}"
    p = h.percentiles_ms()
    return f"{h.count:>6} {p['p50']:>5.2f}/{p['p99']:>5.2f}"


def format_stage_table(export: dict) -> str:
    """Per-stage count p50/p99 (ms): one column per worker + merged."""
    workers = export.get("workers") or {}
    idxs = sorted(workers, key=lambda s: int(s))
    fleet = export.get("fleet_hists") or {}
    lines = [
        f"fleet stages (count p50/p99 ms), node {export.get('node', '?')}:",
        "stage      " + " ".join(f"{'w' + i:>18}" for i in idxs)
        + f" {'fleet':>18}",
    ]
    for stage in KNOWN_STAGES:
        key = f"span_stage_{stage}_latency"
        row = [_cell(_hist((workers[i].get("hists") or {}).get(key)))
               for i in idxs]
        row.append(_cell(_hist(fleet.get(f"fleet_{key}"))))
        if all(c.strip() == "-" for c in row):
            continue  # stage idle fleet-wide: keep the table tight
        lines.append(f"{stage:<10} " + " ".join(row))
    for name, label in (("shm_ring_roundtrip", "ring e2e"),
                        ("loop_lag", "loop_lag"),
                        ("gc_pause", "gc_pause"),
                        ("engine_tick_latency", "tick")):
        row = [_cell(_hist((workers[i].get("hists") or {}).get(name)))
               for i in idxs]
        row.append(_cell(_hist(fleet.get(f"fleet_{name}"))))
        if any(c.strip() != "-" for c in row):
            lines.append(f"{label:<10} " + " ".join(row))
    return "\n".join(lines)


def format_lanes(export: dict) -> str:
    """Hub drain/fusion telemetry + per-lane ring health."""
    hub = export.get("hub") or {}
    if not hub:
        return "no hub telemetry (shm plane off)"
    st = hub.get("stats") or {}
    lines = [
        f"hub: {st.get('ticks', 0)} ticks in {st.get('groups', 0)} "
        f"fused groups, {st.get('res_drops', 0)} result drops, "
        f"{st.get('reclaims', 0)} reclaims",
    ]
    gs = st.get("group_sizes") or {}
    if gs:
        total = sum(gs.values()) or 1
        dist = " ".join(
            f"{k}x:{v} ({v / total * 100.0:.0f}%)"
            for k, v in sorted(gs.items(), key=lambda kv: int(kv[0]))
        )
        lines.append(f"fusion group sizes: {dist}")
    dc = st.get("drain_cycle_ms")
    if dc:
        lines.append(
            f"drain cycle: p50 {dc['p50']:.3f} ms, "
            f"p99 {dc['p99']:.3f} ms"
        )
    lanes = hub.get("lanes") or {}
    if lanes:
        lines.append(
            f"{'lane':<5} {'submit':>7} {'result':>7} {'acks':>6} "
            f"{'filters':>8}"
        )
        for i in sorted(lanes, key=lambda s: int(s)):
            d = lanes[i]
            lines.append(
                f"{i:<5} {d.get('submit_depth', 0):>7} "
                f"{d.get('result_depth', 0):>7} "
                f"{d.get('pending_acks', 0):>6} "
                f"{d.get('filters', 0):>8}"
            )
    return "\n".join(lines)


def format_waterfalls(export: dict, k: int = 8) -> str:
    """Cross-process slowest spans, worker-tagged, slowest first."""
    rows: List[tuple] = []
    for i, w in (export.get("workers") or {}).items():
        for rec in w.get("spans_slowest") or []:
            rows.append((rec.get("total_ms", 0.0), i, rec))
    if not rows:
        return "no completed spans reported by any worker"
    rows.sort(reverse=True, key=lambda r: r[0])
    lines = ["slowest spans fleet-wide (per-stage ms):"]
    for total, i, rec in rows[:k]:
        waterfall = " ".join(
            f"{s}={rec['stages'][s]:.3f}"
            for s in KNOWN_STAGES if s in (rec.get("stages") or {})
        )
        lines.append(
            f"  w{i} {total:>9.3f}ms {rec.get('topic', '?'):<28} "
            f"{waterfall}"
        )
    return "\n".join(lines)


def dump(export: dict, slow: int = 8) -> str:
    return "\n\n".join([
        format_stage_table(export),
        format_lanes(export),
        format_waterfalls(export, slow),
    ])


def to_json(export: dict) -> str:
    """Schema-pinned machine-readable re-emit (CI/soak gates parse
    this; the pin means a field rename is a breaking change here, not
    in every downstream jq)."""
    out = dict(export)
    out["schema"] = SCHEMA
    return json.dumps(out, indent=2, sort_keys=True)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="render a fleet observability export"
    )
    ap.add_argument("path", help="JSON from WireSupervisor.fleet_export"
                                 " / bench.py --spans-shm --emit-stats")
    ap.add_argument("--slow", type=int, default=8,
                    help="tail spans to show (default 8)")
    ap.add_argument("--json", action="store_true",
                    help="emit schema-pinned JSON instead of tables")
    ns = ap.parse_args()
    with open(ns.path, "r", encoding="utf-8") as f:
        export = json.load(f)
    # bench exports nest the fleet dump under "fleet"
    if "workers" not in export and "fleet" in export:
        export = export["fleet"]
    if ns.json:
        print(to_json(export))
    else:
        print(dump(export, slow=ns.slow))


if __name__ == "__main__":
    main()
