"""Benchmark: TPU topic-match engine vs CPU trie baseline.

Reproduces the reference's in-tree microbench methodology
(`apps/emqx/src/emqx_broker_bench.erl`: N subscribers insert filters, M
publishers measure LookupRps) across the five workload configs of
`BASELINE.json`:

  1  1k exact-match subs, single-level topics
  2  100k subs, 6-level topics, 20% single-level '+' wildcards  (HEADLINE)
  3  1M subs, mixed '+'/'#' wildcards, shared-subscription groups
  4  10M subs, Zipf-skewed publish topic distribution
  5  10M subs with 5%/sec subscribe/unsubscribe churn

Default run = ALL FIVE configs (one fresh subprocess each) -> writes
BENCH_TABLE.md, then prints the config-2 headline as ONE JSON line (the
driver contract plus informational extras):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "device": "tpu", "p99_ms": N, "kernel_rps": N, ...}

value/vs_baseline are the END-TO-END `engine.match()` rate (host hash ->
upload -> fused device dispatch -> compact return -> exact verification),
pipelined; the raw device-kernel rate is reported alongside.

Refuses to record a CPU number (exit != 0) unless BENCH_ALLOW_CPU=1.

  python bench.py                   # all 5 -> BENCH_TABLE.md + headline line
  python bench.py --config 3        # one JSON line for config 3
  python bench.py --subs 1000000    # cap the big configs' table size

vs_baseline = TPU route-lookups/sec over the CPU dict-trie baseline (the
reference's ETS-trie analog) measured in the same process.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import statistics
import sys
import time

import numpy as np

BATCH = 4096
ITERS = 200
WARMUP = 5
CPU_LOOKUPS = 3000


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class ChurnPacer:
    """Wall-clock churn pacing shared by the CPU baseline and the engine
    north-star sweep: both sides owe `rate` ops/sec of churn, accrued by
    elapsed time — ONE implementation so the fairness claim can't drift.

    The backlog is BOUNDED: when the applier cannot sustain `rate`,
    unbounded debt would make every loop diverge (each pass accrues more
    churn than it retires — the config-5 CPU trie at 10M sits right at
    the 500k ops/s demand).  Debt beyond `max_backlog` seconds' worth is
    shed and counted in `.shed`; each call retires the FULL remaining
    debt (a per-call cap would throttle the pacer itself and report the
    cap, not the applier's capacity), so the measured loop always
    progresses and the ACHIEVED churn rate is applier-limited."""

    def __init__(self, rate: float, max_backlog: float = 0.25):
        self.rate = rate
        self.last = time.time()
        self.debt = 0.0
        self.shed = 0
        self.max_backlog = max_backlog

    def owed(self, now: float) -> int:
        self.debt += (now - self.last) * self.rate
        self.last = now
        cap = self.rate * self.max_backlog
        if self.debt > cap:
            self.shed += int(self.debt - cap)
            self.debt = cap
        n = int(self.debt)
        self.debt -= n
        return n


def _pool_width() -> int:
    """Native worker-pool width (workers + caller), 1 without the lib —
    churn rows carry their worker count (ETPU_POOL_THREADS pins it)."""
    from emqx_tpu.ops import native

    return native.pool_width()


def pick_north_star(ns_rows, cpu_rps, churn_target: float = 0.0):
    """(best_row, passed): the highest-throughput row meeting ALL gates
    (>=10x CPU, p99 < 2 ms, and — when the workload churns — achieved
    churn >= 90% of target, so a row cannot buy throughput by shedding
    its own load), else the highest-throughput row overall.  Single
    source for the headline JSON and BENCH_TABLE.md."""
    if not ns_rows:
        return None, False
    passing = [
        r for r in ns_rows
        if r["p99_ms"] < 2.0
        and r["rps"] >= 10 * cpu_rps
        and (not churn_target
             or r.get("churn_rps", 0.0) >= 0.9 * churn_target)
    ]
    if passing:
        return max(passing, key=lambda r: r["rps"]), True
    return max(ns_rows, key=lambda r: r["rps"]), False


# ------------------------------------------------------------- populations

def pop_exact_1k(rng):
    filters = [f"chan{i}" for i in range(1_000)]
    topics = lambda: [f"chan{rng.randint(0, 999)}" for _ in range(BATCH)]
    return filters, topics


def pop_wild_100k(rng, n=100_000):
    """6-level topics, 20% '+', 5% '#' (the original headline config)."""
    filters = []
    for i in range(n):
        ws = [
            "device",
            str(rng.randint(0, 999)),
            rng.choice(["temp", "hum", "acc", "gps"]),
            str(rng.randint(0, 99)),
            rng.choice(["raw", "agg"]),
            str(i % 4096),
        ]
        r = rng.random()
        if r < 0.20:
            ws[rng.randint(1, 5)] = "+"
        elif r < 0.25:
            ws = ws[: rng.randint(2, 5)] + ["#"]
        filters.append("/".join(ws))
    # uniqueness: suffix duplicates with an id level (the table holds one
    # entry per unique filter; the broker refcounts duplicate subscribers)
    seen, out = set(), []
    for i, f in enumerate(filters):
        if f in seen:
            f = f + f"/u{i}"
        seen.add(f)
        out.append(f)

    def topics():
        return [
            "/".join([
                "device", str(rng.randint(0, 999)),
                rng.choice(["temp", "hum", "acc", "gps"]),
                str(rng.randint(0, 99)), rng.choice(["raw", "agg"]),
                str(rng.randint(0, 4095)),
            ])
            for _ in range(BATCH)
        ]

    return out, topics


def pop_mixed(rng, n):
    """Config 3: mixed '+'/'#' + shared-subscription groups.

    Shared subs ($share/<group>/<filter>) route on the inner filter
    (`emqx_shared_sub.erl`); group pick happens host-side after match, so
    the match-engine workload is the deduped inner filter set.
    """
    filters = []
    for i in range(n):
        r = rng.random()
        base = ["site", str(i % 997), "line", str(rng.randint(0, 99)),
                "sensor", str(i)]
        if r < 0.30:
            base[rng.choice([1, 3])] = "+"
        if r < 0.10:
            base = base[:4] + ["#"]
        filters.append("/".join(base) + (f"/u{i}" if r >= 0.10 and r < 0.30 else ""))
    seen, out = set(), []
    for i, f in enumerate(filters):
        if f in seen:
            f = f + f"/u{i}"
        seen.add(f)
        out.append(f)

    def topics():
        return [
            f"site/{rng.randint(0, 996)}/line/{rng.randint(0, 99)}/sensor/{rng.randint(0, n)}"
            for _ in range(BATCH)
        ]

    return out, topics


def pop_zipf(rng, n):
    """Config 4: big sub table, Zipf-skewed publish topics (hot topics
    dominate, like production MQTT fan-in)."""
    filters, topics_fn = pop_mixed(rng, n)
    zipf_ids = np.random.default_rng(5).zipf(1.3, size=200_000)

    def topics():
        idx = np.random.default_rng(rng.randint(0, 1 << 30)).integers(
            0, len(zipf_ids), BATCH)
        return [
            f"site/{int(zipf_ids[i]) % 997}/line/{int(zipf_ids[i]) % 100}/sensor/{int(zipf_ids[i]) % n}"
            for i in idx
        ]

    return filters, topics


# ------------------------------------------------------------ measurement

def cpu_baseline(filters, topics_fn, churn_frac=0.0, churn_pool=None):
    """Single-threaded CPU dict-trie baseline (the ETS-trie analog).

    When the workload includes churn (config 5: "incremental trie
    rebuild under load"), the baseline pays the SAME churn rate the
    engine does — `churn_frac` of the population per second, paced by
    its own wall clock — so the lookup rate is the effective rate under
    load on both sides, not match-only for one and match+churn for the
    other."""
    from emqx_tpu.models.reference import CpuTrieIndex

    # small populations: a single timed insert is ~1 ms on this host,
    # inside VM noise — take best-of-5 fresh builds (both sides of the
    # insert comparison use the same rule; see run_engine)
    reps = 5 if len(filters) < 10_000 else 1
    cpu_insert_rps = 0.0
    for _ in range(reps):
        trie = CpuTrieIndex()
        ins0 = time.time()
        for i, f in enumerate(filters):
            trie.insert(f, i)
        cpu_insert_rps = max(
            cpu_insert_rps, len(filters) / (time.time() - ins0)
        )
    cpu_topics = topics_fn()[:CPU_LOOKUPS]
    # clean lookup rate first: the kernel/device/insert comparison
    # columns baseline against an UNLOADED trie (config 5's churned rate
    # below collapses toward zero — honest for the under-load row, but a
    # "match speedup" computed against a drowning baseline is noise)
    m0 = time.time()
    hits = 0
    for t in cpu_topics:
        hits += len(trie.match(t))
    cpu_rps_clean = len(cpu_topics) / (time.time() - m0)
    target_cps = churn_frac * len(filters)  # churn ops/sec to sustain
    cpu_rps = cpu_rps_clean
    churn_i = 0
    fid_base = len(filters)
    present: dict = {}
    churn_events = 0
    pacer = ChurnPacer(target_cps)
    if target_cps and churn_pool:
        m0 = time.time()
        pacer.last = m0
        for k, t in enumerate(cpu_topics):
            hits += len(trie.match(t))
            if (k & 7) == 7:
                n_ops = pacer.owed(time.time())
                for _ in range(n_ops):
                    f = churn_pool[churn_i % len(churn_pool)]
                    fid = present.pop(f, None)
                    if fid is None:
                        fid = fid_base + churn_i
                        trie.insert(f, fid)
                        present[f] = fid
                    else:
                        trie.delete(f, fid)
                    churn_i += 1
                    churn_events += 1
        wall = time.time() - m0
        cpu_rps = len(cpu_topics) / wall
        log(f"cpu churned: {churn_events/wall:,.0f} churn/s applied "
            f"(target {target_cps:,.0f}, shed {pacer.shed})")
    log(f"cpu baseline: insert {cpu_insert_rps:,.0f}/s, lookup "
        f"{cpu_rps:,.0f}/s under load, {cpu_rps_clean:,.0f}/s clean "
        f"({hits} hits, {churn_events} churn events)")
    return cpu_insert_rps, cpu_rps, cpu_rps_clean


_DEVICE = None


def init_device():
    """Find an accelerator, retrying init; never silently bench CPU.

    Round-1's driver artifact recorded a CPU number because a transient
    backend-init failure fell through to CPU.  Now: retry (clearing cached
    backend errors between attempts), and if no accelerator appears, abort
    unless BENCH_ALLOW_CPU=1 is set explicitly.
    """
    global _DEVICE
    if _DEVICE is not None:
        return _DEVICE
    import jax

    last = None
    for attempt in range(5):
        try:
            for d in jax.devices():
                if d.platform != "cpu":
                    _DEVICE = d
                    return d
            last = f"only cpu devices visible: {jax.devices()}"
        except RuntimeError as e:
            last = e
        log(f"accelerator init attempt {attempt + 1}/5 failed: {last}")
        if attempt == 4:
            break
        try:  # reset cached backends/errors so the retry is real (jax>=0.9)
            from jax.extend.backend import clear_backends
        except ImportError:
            clear_backends = getattr(jax, "clear_backends", lambda: None)
        try:
            clear_backends()
        except Exception as ce:
            log(f"clear_backends failed: {ce}")
        time.sleep(2 * (attempt + 1))
    if os.environ.get("BENCH_ALLOW_CPU"):
        log("BENCH_ALLOW_CPU=1: benchmarking CPU — NOT a TPU number")
        jax.config.update("jax_platforms", "cpu")
        _DEVICE = jax.devices()[0]
        return _DEVICE
    raise SystemExit(
        f"no accelerator after 5 attempts ({last}); refusing to record a "
        "CPU number as the driver benchmark (set BENCH_ALLOW_CPU=1 to "
        "override for local runs)"
    )


def run_engine(filters, topics_fn, churn_frac=0.0, churn_pool=None):
    """Measures BOTH rates (round-2 VERDICT weak #1):

    * kernel  — `match_batch_jit` on pre-hashed, pre-uploaded batches
      (the device data-plane roofline);
    * e2e     — `engine.match()` from topic STRINGS with verification ON
      (native hash -> device_put -> fused dispatch -> compact return ->
      native exact verify), pipelined two deep so host hashing of batch
      N overlaps device compute of batch N-1.

    Config 5's churn runs inside the e2e loop through the fused
    delta+match dispatch (`ops.match.fused_step_sparse`): a churn tick
    costs the same single round trip as a pure match tick.
    """
    import jax

    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.ops import hashing
    from emqx_tpu.ops.match import TopicBatch, match_batch_jit

    dev = init_device()
    log(f"device: {dev.platform} {dev}")

    eng = TopicMatchEngine(device=dev)
    if os.environ.get("BENCH_NO_FLIGHT"):
        # A/B the recorder's overhead (acceptance: < 2% on config 1):
        # BENCH_NO_FLIGHT=1 python bench.py --config 1
        eng.flight = None
    # lib/registry load + first-call setup is process-lifetime cost, not
    # insert cost — at config 1's 1k filters it was half the timed window
    eng.add_filter("$bench/warm")
    eng.remove_filter("$bench/warm")
    ins0 = time.time()
    eng.add_filters(filters)
    insert_rps = len(filters) / (time.time() - ins0)
    if len(filters) < 10_000:
        # best-of-5 fresh engines: same noise rule as the cpu side
        for _ in range(4):
            e2 = TopicMatchEngine(device=dev)
            e2.add_filter("$bench/warm")
            e2.remove_filter("$bench/warm")
            ins0 = time.time()
            e2.add_filters(filters)
            insert_rps = max(
                insert_rps, len(filters) / (time.time() - ins0)
            )
    log(f"engine insert (bulk): {insert_rps:,.0f}/s")
    tables = eng.sync_device()

    n_batches = 8
    batches_str = [topics_fn() for _ in range(n_batches)]

    # pre-hash for the kernel-only section (hash rate logged separately)
    batches = []
    hash_secs = 0.0
    for ts in batches_str:
        h0 = time.time()
        # C++ fast path (split+fnv+mix in one threaded pass) when built
        ta, tb, ln, dl = hashing.hash_topics(eng.space, ts)
        hash_secs += time.time() - h0
        batches.append(
            TopicBatch(*(jax.device_put(x, dev) for x in (ta, tb, ln, dl)))
        )
    host_hash_rps = n_batches * BATCH / hash_secs

    # ---------------------------------------------------- kernel section
    c0 = time.time()
    out = match_batch_jit(tables, batches[0])
    out.block_until_ready()
    log(f"first compile+run: {time.time()-c0:.1f}s")
    for i in range(WARMUP):
        match_batch_jit(tables, batches[i % n_batches]).block_until_ready()

    lat = []
    r0 = time.time()
    for i in range(ITERS):
        b0 = time.time()
        out = match_batch_jit(tables, batches[i % n_batches])
        out.block_until_ready()
        lat.append(time.time() - b0)
    elapsed = time.time() - r0
    kernel_rps = ITERS * BATCH / elapsed
    kernel_p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    matched = np.asarray(out)
    log(f"kernel: {kernel_rps:,.0f} lookups/s ({elapsed*1e3/ITERS:.2f} ms/"
        f"batch of {BATCH}, p99 {kernel_p99:.2f} ms); host hash "
        f"{host_hash_rps:,.0f}/s; sample hits {(matched >= 0).sum()}")
    del tables, out  # drop kernel-section aliases before the e2e section

    # ---------------------------------------------------------- link probe
    # The tunneled dev rig's device->host path is the e2e wall (measured
    # ~5 MB/s + ~100 ms/op, vs ~1.3 GB/s host->device); record it so the
    # e2e numbers can be read against the link, not the design.
    probe = np.zeros(1 << 18, dtype=np.int32)  # 1 MB
    pd = jax.device_put(probe, dev)
    jax.block_until_ready(pd)
    t0 = time.time()
    pd2 = jax.device_put(probe, dev)
    jax.block_until_ready(pd2)
    up_mbs = 1.0 / max(time.time() - t0, 1e-9)
    t0 = time.time()
    np.asarray(pd2)
    down_mbs = 1.0 / max(time.time() - t0, 1e-9)
    log(f"link: host->device {up_mbs:,.0f} MB/s, device->host "
        f"{down_mbs:,.1f} MB/s (1 MB probe)")

    # ------------------------------------------------------- e2e section
    churn_events = 0
    k_churn = 0
    if churn_frac and churn_pool:
        k_churn = max(1, int(len(filters) * churn_frac / ITERS))

    churn_i = 0

    def churn_tick_n(k: int):
        nonlocal churn_i, churn_events
        adds, removes = [], []
        for j in range(k):
            f = churn_pool[(churn_i + j) % len(churn_pool)]
            (removes if eng.fid_of(f) is not None else adds).append(f)
        churn_i += k
        churn_events += k
        eng.apply_churn(adds, removes)

    def churn_tick(scale: int = 1):
        churn_tick_n(k_churn * scale)

    # warmup compiles the e2e shapes (incl. the fused churn dispatch)
    if k_churn:
        churn_tick()
    eng.match(batches_str[0])
    eng.match(batches_str[1])

    E2E_LAT_ITERS = 30
    lat = []
    for i in range(E2E_LAT_ITERS):
        if k_churn:
            churn_tick()
        b0 = time.time()
        eng.match(batches_str[i % n_batches])
        lat.append(time.time() - b0)
    e2e_p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    e2e_p50 = float(np.percentile(np.array(lat) * 1e3, 50))

    # throughput: bigger ticks amortize the per-get latency (the broker
    # controls its own publish batch size; over this link bigger is
    # strictly better until the 5 MB/s downlink is saturated)
    E2E_MULT = 32  # 131072 topics per tick
    n_big = 4
    big_batches = []
    for i in range(n_big):
        big = []
        for _ in range(E2E_MULT):
            big.extend(topics_fn())
        big_batches.append(big)
    eng.match(big_batches[0])  # compile the big-tick shapes

    E2E_ITERS = 20
    DEPTH = 3  # in-flight ticks: host verify of N-3 overlaps N-1's transfers
    pending = []
    res = None
    r0 = time.time()
    for i in range(E2E_ITERS):
        if k_churn:
            churn_tick(E2E_MULT)
        pending.append(eng.match_submit(big_batches[i % n_big]))
        if len(pending) >= DEPTH:
            # raw per-topic fid lists: what broker dispatch consumes
            res = eng.match_collect_raw(pending.pop(0))
    while pending:
        res = eng.match_collect_raw(pending.pop(0))
    e2e_elapsed = time.time() - r0
    e2e_rps = E2E_ITERS * E2E_MULT * BATCH / e2e_elapsed
    n_hits = sum(len(s) for s in res)
    log(f"e2e:    {e2e_rps:,.0f} lookups/s "
        f"({e2e_elapsed*1e3/E2E_ITERS:.1f} ms/tick of {E2E_MULT*BATCH:,} "
        f"pipelined; p99 {e2e_p99:.2f} ms unpipelined at {BATCH}); "
        f"verify on, collisions {eng.collision_count}; churn events "
        f"{churn_events}; sample hits {n_hits}")

    # ------------------------------------------------------ hybrid section
    # Production default (broker.hybrid=true): measured-rate arbitration
    # between the fused native host probe and the device dispatch.  On a
    # degraded link the arbiter serves host-side (the reference never
    # pays a wire to match, emqx_router.erl:127-140) while probes keep
    # the HBM mirror warm; on co-located hardware it serves device-side.
    import gc

    # mirror the node runtime's dedicated-process GC tuning (NodeRuntime
    # start(): freeze the resident object graph, raise gen0 so young-gen
    # sweeps don't land in the match path's p99)
    gc.collect()
    gc.freeze()
    _g0, _g1, _g2 = gc.get_threshold()
    gc.set_threshold(50_000, _g1, _g2)
    eng.hybrid = True
    eng.match(batches_str[0])  # arbiter measures; probe dispatched
    eng.match(batches_str[1])
    # bucket-derived percentiles over the SAME ticks as the ad-hoc
    # np.percentile numbers: the engine's hist_tick (observe/flight.py)
    # is the telemetry production reads, so BENCH and live dashboards
    # report from one implementation.  (Config 5's churn_tick runs
    # outside the engine tick, so its wall-clock samples include churn
    # while the histogram holds pure match ticks.)
    eng.hist_tick.reset()
    lat = []
    for i in range(E2E_LAT_ITERS):
        if k_churn:
            churn_tick()
        b0 = time.time()
        eng.match(batches_str[i % n_batches])
        lat.append(time.time() - b0)
    hyb_p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    hyb_p50 = float(np.percentile(np.array(lat) * 1e3, 50))
    hist_p50 = eng.hist_tick.quantile(0.50) * 1e3
    hist_p99 = eng.hist_tick.quantile(0.99) * 1e3
    # interactive-tick latency: the broker's tick is SMALL at interactive
    # publish rates (batch_delay closes it within ~2 ms); a 4096 batch is
    # the throughput shape, 512 is the latency shape
    small = [b[:512] for b in batches_str]
    eng.match_collect_raw(eng.match_submit(small[0]))
    lat = []
    for i in range(40):
        b0 = time.time()
        eng.match_collect_raw(eng.match_submit(small[i % n_batches]))
        lat.append(time.time() - b0)
    hyb_p99_small = float(np.percentile(np.array(lat) * 1e3, 99))
    pending = []
    r0 = time.time()
    for i in range(E2E_ITERS):
        if k_churn:
            churn_tick(E2E_MULT)
        pending.append(eng.match_submit(big_batches[i % n_big]))
        if len(pending) >= DEPTH:
            res = eng.match_collect_raw(pending.pop(0))
    while pending:
        res = eng.match_collect_raw(pending.pop(0))
    hyb_elapsed = time.time() - r0
    hyb_rps = E2E_ITERS * E2E_MULT * BATCH / hyb_elapsed
    log(f"hybrid: {hyb_rps:,.0f} lookups/s "
        f"({hyb_elapsed*1e3/E2E_ITERS:.1f} ms/tick of {E2E_MULT*BATCH:,}; "
        f"p99 {hyb_p99:.2f} ms at {BATCH}); served host={eng.host_serve_count} "
        f"device={eng.dev_serve_count} timeouts={eng.dev_timeout_count}; "
        f"collisions {eng.collision_count}; sample hits "
        f"{sum(len(s) for s in res)}")
    log(f"flight:  bucket-derived p50 {hist_p50:.2f} / p99 {hist_p99:.2f} ms "
        f"(ad-hoc {hyb_p50:.2f} / {hyb_p99:.2f}); "
        f"flips={eng.path_flips} probes={eng.probe_count}"
        + ("" if eng.flight is None else
           f"; ring bytes up={eng.flight.bytes_up_total:,} "
           f"down={eng.flight.bytes_down_total:,}"))

    # -------------------------------------------------- north-star sweep
    # BASELINE.md gates BOTH throughput (>=10x CPU) and p99 (<2 ms) — at
    # ONE operating point.  Sweep tick sizes measuring sustained rate AND
    # per-tick latency at the SAME tick, production hybrid path, churn
    # paced by wall clock (churn_frac of the population per second, the
    # workload's definition) so config 5's rate is effective-under-load.
    # Each tick size runs THREE repetitions and the row is the median-
    # by-throughput rep (VERDICT r5: a single rep flipped the gate
    # inside run-to-run noise — 10.2x committed vs 9.5x captured); all
    # three land in the JSON under "reps" so noise is auditable.
    ns_rows = []
    target_cps = churn_frac * len(filters) if churn_pool else 0.0
    for tick in (512, 1024, 2048, 4096):
        tb = [b[:tick] for b in batches_str] if tick <= BATCH else None
        if tb is None:
            continue
        eng.match_collect_raw(eng.match_submit(tb[0]))  # warm shape
        iters = max(10, min(100, int(700_000 / tick)))
        reps = []
        for _rep in range(3):
            lat = []
            churn_before = churn_events
            pacer = ChurnPacer(target_cps)
            shed_seen = 0
            t0 = time.time()
            pacer.last = t0
            for i in range(iters):
                b0 = time.time()
                if target_cps:
                    n_ops = pacer.owed(b0)
                    if pacer.shed > shed_seen:
                        # shed load is an ENGINE-visible event now: the
                        # tracepoint + counter + flight tick row carry it
                        eng.note_churn_shed(pacer.shed - shed_seen)
                        shed_seen = pacer.shed
                    if n_ops:
                        churn_tick_n(n_ops)
                eng.match_collect_raw(eng.match_submit(tb[i % len(tb)]))
                lat.append(time.time() - b0)
            wall = time.time() - t0
            rep = {
                "rps": iters * tick / wall,
                "p99_ms": float(np.percentile(np.array(lat) * 1e3, 99)),
            }
            if target_cps:
                rep["churn_rps"] = (churn_events - churn_before) / wall
                rep["churn_shed"] = pacer.shed
                rep["churn_shed_rps"] = pacer.shed / wall
            reps.append(rep)
        med = sorted(reps, key=lambda r: r["rps"])[1]
        row = {"tick": tick, **med, "reps": reps}
        if target_cps:
            log(f"north-star tick {tick}: {row['rps']:,.0f} lookups/s "
                f"(median of {[round(r['rps']) for r in reps]}), p99 "
                f"{row['p99_ms']:.2f} ms; churn {row['churn_rps']:,.0f}/s "
                f"applied (target {target_cps:,.0f}, "
                f"shed {row['churn_shed']})")
        else:
            log(f"north-star tick {tick}: {row['rps']:,.0f} lookups/s "
                f"(median of {[round(r['rps']) for r in reps]}), "
                f"p99 {row['p99_ms']:.2f} ms")
        ns_rows.append(row)
    return {
        "ns_rows": ns_rows,
        "churn_target": target_cps,
        # parallel-churn-plane provenance: the north-star churn rows are
        # per-worker capacity statements, so they carry their worker
        # count (ETPU_POOL_THREADS-pinnable) and plane mode
        "churn_workers": _pool_width(),
        "churn_plane": eng._plane is not None,
        "churn_shed_total": eng.churn_shed,
        "tpu_rps": hyb_rps,  # headline: the production (hybrid) match rate
        "p99_ms": hyb_p99,
        "p99_small_ms": hyb_p99_small,
        "p50_ms": hyb_p50,
        # telemetry-plane percentiles (engine hist_tick log2 buckets):
        # must agree with the ad-hoc numbers within one bucket width
        "hist_p50_ms": hist_p50,
        "hist_p99_ms": hist_p99,
        "path_flips": eng.path_flips,
        "flight": None if eng.flight is None else eng.flight.summary(),
        "dev_e2e_rps": e2e_rps,
        "dev_p99_ms": e2e_p99,
        "dev_p50_ms": e2e_p50,
        "hybrid_host_serves": eng.host_serve_count,
        "hybrid_dev_serves": eng.dev_serve_count,
        "kernel_rps": kernel_rps,
        "kernel_p99_ms": kernel_p99,
        "insert_rps": insert_rps,
        "host_hash_rps": host_hash_rps,
        "link_up_mbs": up_mbs,
        "link_down_mbs": down_mbs,
        "device": dev.platform,
        # core-count honesty (VERDICT r4 #2): the CPU baseline is ONE
        # thread; the host-probe path uses the native pool = all hardware
        # threads, capped at 16 (pool.h) — on a 1-core host both are 1
        "host_threads": os.cpu_count() or 1,
        "match_threads": min(16, os.cpu_count() or 1),
        "baseline_threads": 1,
    }


def run_sharded(subs_cap=None, workload=2):
    """BASELINE workloads on the mesh-sharded engine (8 virtual CPU
    devices — the same mesh the driver dry-runs; real-ICI numbers need
    a real v5e-8).  `workload` picks the population: 2 = 100k wildcard,
    3 = 1M mixed/shared-groups, 5 = 1M mixed + 5%/sec churn (configs 3/5
    run at 1M resident — the virtual mesh shares one host's RAM and
    cores, so 10M would measure swap, not the dispatch path).

    Emits a PHASE BREAKDOWN per tick (VERDICT r4 #5): prep (native
    split+hash + packed staging upload + dispatch call), device compute,
    resolve fetch, verify+assembly — so the p99 can be read against its
    actual bucket — and measures e2e at BOTH pipeline_depth=1 (lock-
    step) and the engine's window depth, with flight-recorder occupancy,
    so the pipeline's contribution is a measured ratio, not a claim.
    """
    import os
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices("cpu")
    assert len(devs) >= 8, devs

    from emqx_tpu.parallel import sharded as shmod
    from emqx_tpu.parallel.sharded import ShardedMatchEngine

    rng = random.Random(1236)
    churn_frac, churn_pool = 0.0, None
    if workload == 2:
        filters, topics_fn = pop_wild_100k(rng, subs_cap or 100_000)
    elif workload == 3:
        filters, topics_fn = pop_mixed(rng, subs_cap or 1_000_000)
    elif workload == 5:
        filters, topics_fn = pop_mixed(rng, subs_cap or 1_000_000)
        churn_frac = 0.05
        churn_pool = [f"churn/{i}/+" for i in range(50_000)]
    else:
        raise SystemExit(f"sharded workload {workload} unsupported")
    cpu_insert, cpu_rps, cpu_clean = cpu_baseline(filters, topics_fn,
                                                  churn_frac, churn_pool)

    eng = ShardedMatchEngine(kcap=64)
    ins0 = time.time()
    eng.add_filters(filters)
    insert_rps = len(filters) / (time.time() - ins0)
    log(f"sharded insert (bulk): {insert_rps:,.0f}/s over {eng.D} devices")
    if churn_pool:
        # pre-grow table capacity for the churn pool's peak population:
        # otherwise the measured window pays one-off load-factor
        # rebuilds (amortized growth, not steady-state churn)
        eng.add_filters(churn_pool)
        eng.apply_churn([], churn_pool)

    import gc

    gc.collect()
    gc.freeze()
    TICK = 512  # latency shape: the broker's interactive tick
    batches = [topics_fn()[:TICK] for _ in range(8)]
    c0 = time.time()
    eng.match(batches[0])
    log(f"first compile+run: {time.time()-c0:.1f}s")
    eng.match(batches[1])
    # settle the adaptive kcap before any timed window: the shrink
    # toward observed traffic re-jits the (bounded) kcap variant once,
    # a first-boot cost that must not land mid-measurement
    for i in range(eng.kcap_adapt_interval + 2):
        eng.match(batches[i % 8])

    # phase breakdown (pure match path, no churn, lock-step so every
    # phase is exposed).  PR 12 re-attribution: prep = the fused native
    # prep sub-stages ONLY — hash (split+hash+memo+dedup), pack
    # (staging-buffer gather+pad), submit (group assembly + device_put
    # handoff) — while the mesh-execute call itself (which on a 1-core
    # host runs synchronously INSIDE the pjit call and used to be
    # lumped into "prep", mis-reading as a 7.6 ms prep blob) now lands
    # in the dispatch column where it belongs.  fetch = resolve
    # (device->host of the live compact slice + any overflow refetch),
    # verify = registry exact-check + row assembly.
    prep_s = disp_s = fetch_s = verify_s = 0.0
    ph_hash = ph_pack = ph_sub = 0.0
    PH_ITERS = 15
    for i in range(PH_ITERS):
        topics = batches[i % 8]
        p0 = time.perf_counter()
        pend = eng.match_submit(topics)
        p1 = time.perf_counter()
        g = pend.group
        if g is not None and g.hits is not None:
            jax.block_until_ready((g.hits, g.counts))
        p2 = time.perf_counter()
        eng._resolve(pend)
        p3 = time.perf_counter()
        eng.match_collect_raw(pend)
        p4 = time.perf_counter()
        sub = pend.prep_hash_s + pend.prep_pack_s + pend.prep_put_s
        ph_hash += pend.prep_hash_s
        ph_pack += pend.prep_pack_s
        ph_sub += pend.prep_put_s
        prep_s += sub
        disp_s += max(p1 - p0 - sub, 0.0) + (p2 - p1)
        fetch_s += p3 - p2
        verify_s += p4 - p3
    phases = {
        "prep_ms": prep_s / PH_ITERS * 1e3,
        "prep_hash_ms": ph_hash / PH_ITERS * 1e3,
        "prep_pack_ms": ph_pack / PH_ITERS * 1e3,
        "prep_submit_ms": ph_sub / PH_ITERS * 1e3,
        "dispatch_ms": disp_s / PH_ITERS * 1e3,
        "fetch_ms": fetch_s / PH_ITERS * 1e3,
        "verify_ms": verify_s / PH_ITERS * 1e3,
    }
    log(f"sharded phases/tick({TICK}): " + "  ".join(
        f"{k} {v:.2f}" for k, v in phases.items())
        + f"  (kcap {eng._kcap_dyn})")

    # churn helper (workload 5): wall-clock paced, like the north-star
    target_cps = churn_frac * len(filters) if churn_pool else 0.0
    churn_i = 0

    def churn_tick_n(k: int):
        nonlocal churn_i
        adds, removes = [], []
        for j in range(k):
            fl = churn_pool[(churn_i + j) % len(churn_pool)]
            (removes if eng.fid_of(fl) is not None else adds).append(fl)
        churn_i += k
        eng.apply_churn(adds, removes)

    if target_cps:
        # warm the fused-dispatch delta-size variants (deltas pad to
        # pow2 K, so the variant set is bounded at log2): each compiles
        # once — the node's persistent XLA cache makes this a
        # first-boot-only cost, so pay it before the timed window
        k = 64
        while k <= 16384:
            churn_tick_n(k)
            eng.match(batches[0])
            k *= 2

    lat = []
    pacer = ChurnPacer(target_cps)
    shed_seen = 0
    pacer.last = time.time()
    for i in range(20):
        b0 = time.time()
        if target_cps:
            n_ops = pacer.owed(b0)
            if pacer.shed > shed_seen:
                eng.note_churn_shed(pacer.shed - shed_seen)
                shed_seen = pacer.shed
            if n_ops:
                churn_tick_n(n_ops)
        eng.match(batches[i % 8])
        lat.append(time.time() - b0)
    p99 = float(np.percentile(np.array(lat) * 1e3, 99))

    # e2e at depth 1 (lock-step) AND at the engine's pipeline window,
    # same host, same run — the depth-N/depth-1 ratio is the pipeline's
    # measured win, and the flight recorder's occupancy column shows how
    # full the window actually ran.  NOTE: on a 1-hardware-thread host
    # (this container) every phase serializes onto the same core, so the
    # ratio reads ~1.0 — the window's overlap needs a second execution
    # resource (real TPU devices, or host cores for the virtual mesh).
    from emqx_tpu.observe.flight import FlightRecorder

    ITERS_S = 40
    SETTLE = 16  # untimed ticks so the adaptive window clamp converges
    REPS = 5  # interleaved A/B/A/B reps: heap/ordering drift (GC, kcap,
    # table growth from churn) lands on BOTH depths instead of biasing
    # whichever runs second — each row is the median rep
    res = None

    eng.prep_timeout = 2.0  # bench boxes: never degrade on scheduling

    def _window(n_iters, pin_ops=None):
        """One pipelined window of n_iters ticks (pacer-paced churn).
        The caller-side pending queue is part of the in-flight window,
        so it follows the engine's adaptive effective depth: when the
        clamp says 1 (churn drains every tick, or deep measured slower)
        holding depth-N resolved ticks would be pure overhead.

        PREP-AHEAD (PR 12): at depth > 1 the loop keeps the engine's
        prep stage primed `effective_depth` ticks ahead — the worker
        packs tick N+1..N+depth while tick N's dispatch runs, and
        consecutive prepped tickets coalesce into ONE mesh dispatch
        (the depth win the A/B controller measures).

        PINNED PACING (`pin_ops`): the wall-clock pacer feeds back —
        one slow tick accrues more churn debt, which makes the next
        tick slower — and on w5 that feedback spread the measured reps
        8.5k–41k lookups/s (PR 12 note).  Measured windows therefore
        apply a FIXED `pin_ops` churn ops per tick, calibrated from
        the settle window's wall clock at the same depth, so every rep
        retires the same work schedule; the achieved churn/s column
        still reports work/wall honestly."""
        nonlocal res
        pacer = ChurnPacer(target_cps)
        pacer.last = time.time()
        shed = 0
        pending = []
        tickets = {}
        next_prep = 0
        prep_occ = 0.0
        c0 = churn_i
        t0 = time.time()
        for i in range(n_iters):
            if target_cps and pin_ops is not None:
                if pin_ops:
                    churn_tick_n(pin_ops)
            elif target_cps:
                n_ops = pacer.owed(time.time())
                if pacer.shed > shed:
                    eng.note_churn_shed(pacer.shed - shed)
                    shed = pacer.shed
                if n_ops:
                    churn_tick_n(n_ops)
            eff = max(1, min(eng.pipeline_depth,
                             getattr(eng, "effective_depth",
                                     eng.pipeline_depth)))
            if eng.pipeline_depth > 1 and (
                eff > 1 or eng._drain_ewma < eng.drain_clamp
            ):
                # prime whenever the LEG is deep and the window can
                # actually fill (not just when the A/B verdict currently
                # says deep — tickets must already be prepped when the
                # controller probes deep mode, or the probe measures a
                # cold ramp instead of the coalesced steady state).  A
                # churn-drain clamp (w5: every tick fuses churn and
                # drains the window) skips priming outright: those
                # dispatches can never coalesce, so staged tickets
                # would be pure handoff overhead.
                ahead = max(eff, 2)
                next_prep = max(next_prep, i)
                while next_prep < n_iters and next_prep < i + ahead:
                    tickets[next_prep] = eng.prep_submit(
                        batches[next_prep % 8]
                    )
                    next_prep += 1
            prep_occ += eng.prep_ready
            pending.append(
                eng.match_submit(batches[i % 8], prep=tickets.pop(i, None))
            )
            while len(pending) >= eff:
                res = eng.match_collect_raw(pending.pop(0))
        while pending:
            res = eng.match_collect_raw(pending.pop(0))
        for tk in tickets.values():  # depth clamped mid-run: unused
            eng.prep_discard(tk)
        return time.time() - t0, churn_i - c0, pacer.shed, \
            prep_occ / max(n_iters, 1)

    if eng.pipeline_depth > 1:
        # warm the coalesced-dispatch kernel variants (the K=2/K=4
        # group shapes compile on first use — a first-boot cost the
        # node's persistent XLA cache absorbs in production, which must
        # not land mid-measurement) with the A/B controller pinned
        # deep; then reset the controller so each measured leg
        # discovers its own verdict from scratch
        saved_streak = eng.depth_win_streak
        eng.depth_win_streak = 0
        eng._dw_deep = True
        eng._dw_cost[False] = float("inf")
        _window(12)
        eng.depth_win_streak = saved_streak
        eng._dw_cost.update({True: None, False: None})
        eng._dw_samples.clear()
        eng._dw_last = None
        eng._dw_streak = 0
        eng._dw_deep = True

    depths = [1] if eng.pipeline_depth == 1 else [1, eng.pipeline_depth]
    rep_rows = {d: [] for d in depths}
    for _rep in range(REPS):
        for depth in depths:
            eng.pipeline_depth = depth
            eng.flight = FlightRecorder(256)
            eng.match(batches[0])  # warm (kcap/bucket variants) + drain
            settle_wall, _, _, _ = _window(SETTLE)
            # pin the pacer for the measured window: the same per-tick
            # churn quota on every rep (calibrated at THIS depth from
            # the settle wall clock) instead of the wall-clock feedback
            # loop that made w5 depth-leg reps spread 8.5k-41k
            pin = (
                max(round(target_cps * settle_wall / SETTLE), 1)
                if target_cps else None
            )
            wall, churn_n, shed, prep_occ = _window(ITERS_S, pin_ops=pin)
            occ = [r["pipe_occ"] for r in eng.flight.recent(ITERS_S)]
            grp = [r["prep_group"] for r in eng.flight.recent(ITERS_S)]
            rep_rows[depth].append({
                "depth": depth,
                "rps": ITERS_S * TICK / wall,
                "churn_rps": churn_n / wall if target_cps else 0.0,
                "churn_shed": shed,
                "occ_mean": float(np.mean(occ)) if occ else 0.0,
                "prep_occ_mean": prep_occ,
                "group_mean": float(np.mean(grp)) if grp else 1.0,
            })
    depth_rows = {}
    for depth, rows in rep_rows.items():
        rows = sorted(rows, key=lambda r: r["rps"])
        row = dict(rows[len(rows) // 2])  # median rep
        row["rps_reps"] = [round(r["rps"]) for r in rows]
        # the row's own noise bar: (max-min)/median over the reps, so
        # a BENCH_TABLE reader sees how much run-to-run spread the
        # median hides (the pinned pacer keeps w5 legs comparable)
        row["rep_spread_pct"] = (
            (rows[-1]["rps"] - rows[0]["rps"]) / row["rps"] * 100.0
            if row["rps"] else 0.0
        )
        depth_rows[depth] = row
        log(f"sharded e2e depth {depth}: {row['rps']:,.0f} lookups/s "
            f"(occ {row['occ_mean']:.1f}/{depth}, "
            f"prep-ahead {row['prep_occ_mean']:.1f}, "
            f"group {row['group_mean']:.1f}, "
            f"reps {row['rps_reps']}); "
            f"churn {row['churn_rps']:,.0f}/s applied "
            f"(target {target_cps:,.0f}, shed {row['churn_shed']})")
    d1 = depth_rows[1]
    dN = depth_rows[max(depth_rows)]
    rps = dN["rps"]
    churn_rps = dN["churn_rps"]
    log(f"sharded e2e: {rps:,.0f} lookups/s at depth {dN['depth']} "
        f"(depth-1 {d1['rps']:,.0f}, ratio {rps / d1['rps']:.2f}x; "
        f"p99 {p99:.2f} ms at {TICK}); collisions {eng.collision_count}; "
        f"prep degraded {eng.prep_degraded}; "
        f"sample hits {sum(len(s) for s in res)}")
    prep_degraded = eng.prep_degraded
    eng.close()  # prep-ahead worker joined, ticket buffers recycled
    return {
        "tpu_rps": rps,
        "rps_depth1": d1["rps"],
        "pipeline_depth": dN["depth"],
        "pipeline_ratio": rps / d1["rps"],
        "occ_mean": dN["occ_mean"],
        "prep_occ_mean": dN["prep_occ_mean"],
        "group_mean": dN["group_mean"],
        "prep_degraded": prep_degraded,
        "depth_rows": sorted(depth_rows.values(), key=lambda r: r["depth"]),
        "p99_ms": p99,
        "tick": TICK,
        "insert_rps": insert_rps,
        "cpu_rps": cpu_rps,
        "cpu_insert_rps": cpu_insert,
        "cpu_rps_clean": cpu_clean,
        "n_filters": len(filters),
        "n_devices": eng.D,
        "workload": workload,
        "churn_events": churn_i,
        "churn_rps": churn_rps,
        "churn_target": target_cps,
        "churn_shed": pacer.shed,
        "churn_workers": _pool_width(),
        "churn_plane": eng._plane is not None,
        "memo_hits": eng.memo_hits,
        "memo_misses": eng.memo_misses,
        "phases": phases,
        "device": "cpu-mesh",
    }


def run_churn_capacity(n_resident=1_000_000, pool_size=100_000):
    """Churn-apply capacity at the CURRENT worker count (pin it with
    ETPU_POOL_THREADS; `--churn` sweeps it via subprocesses).

    Measures the pure `apply_churn` rate — the config 5 bottleneck — on
    the single-chip engine against `n_resident` resident filters, with a
    `pool_size` churn pool applied as alternating precomputed halves so
    only the apply path is timed (no per-op bench glue).  Reports the
    parallel churn plane AND the serial Python-dict fallback from the
    same process, so the plane's win is an A/B on identical state."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.ops import native

    rng = random.Random(4242)
    filters = [
        f"dev/{i}/{rng.choice(['t', 'h', '+'])}/{i % 97}"
        for i in range(n_resident)
    ]
    pool = [f"churn/{i}/+" for i in range(pool_size)]
    half = pool_size // 2
    A, B = pool[:half], pool[half:]
    out = {"workers": native.pool_width(), "n_resident": n_resident,
           "pool_size": pool_size}
    for mode, key in ((True, "plane_rps"), (False, "python_rps")):
        eng = TopicMatchEngine(use_churn_plane=mode)
        if mode and eng._plane is None:
            out[key] = None  # no native lib: fallback only
            continue
        eng.add_filters(filters)
        eng.add_filters(pool)
        eng.apply_churn([], pool)  # pre-grow for the pool's peak
        eng.apply_churn(A, [])     # A present, B absent
        t_apply, n = 0.0, 0
        it = 0
        while t_apply < 3.0:
            adds, removes = (B, A) if it % 2 == 0 else (A, B)
            t0 = time.perf_counter()
            eng.apply_churn(adds, removes)
            t_apply += time.perf_counter() - t0
            n += len(adds) + len(removes)
            it += 1
        out[key] = n / t_apply
        log(f"churn capacity ({'plane' if mode else 'python dicts'}, "
            f"{out['workers']} worker(s)): {out[key]:,.0f} ops/s at "
            f"{n_resident:,} resident")
        del eng
    return out


CHURN_HEADER = "## Churn-apply capacity (parallel churn plane)"


def _update_churn_table(rows, host_threads) -> None:
    """Write the churn worker-sweep section into BENCH_TABLE.md,
    replacing any previous run's section (same ownership discipline as
    the restore/ds sections)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == CHURN_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    r0 = rows[0]
    out += [
        "",
        CHURN_HEADER,
        "",
        "Pure `apply_churn` ops/s (the config 5 bottleneck: route "
        "bookkeeping) on the single-chip engine at "
        f"{r0['n_resident']:,} resident filters, alternating "
        f"{r0['pool_size']:,}-filter add/remove halves so only the "
        "apply path is timed.  `plane` = the sharded native churn plane "
        "(`native/churn.cc`: matchhash-sharded bookkeeping + CAS table "
        "placement on the worker pool, GIL released); `python` = the "
        "serial dict path the plane replaces, same process, same "
        "state.  Workers are pinned per row via ETPU_POOL_THREADS; "
        f"this host exposes {host_threads} hardware thread(s), so rows "
        "beyond that measure oversubscription, not scaling — the "
        ">=1.8x-at-4-workers gate needs a multi-core box.  Measured by "
        "`python bench.py --churn` (`make churn-bench`).",
        "",
        "| workers | plane ops/s | python-dict ops/s | plane vs python |",
        "|---|---|---|---|",
    ]
    for r in rows:
        ratio = (r["plane_rps"] / r["python_rps"]
                 if r.get("plane_rps") and r.get("python_rps") else 0.0)
        out.append(
            f"| {r['workers']} | {r['plane_rps']:,.0f} "
            f"| {r['python_rps']:,.0f} | {ratio:.2f}x |"
        )
    out.append("")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md churn-capacity section")


def run_churn_sweep(workers=(1, 2, 4), subs=None):
    """Worker sweep of run_churn_capacity: one fresh subprocess per
    worker count (the native pool is a process-lifetime singleton, so
    ETPU_POOL_THREADS must be pinned before first use)."""
    import subprocess

    n_resident = subs or 1_000_000
    rows = []
    for w in workers:
        env = dict(os.environ, ETPU_POOL_THREADS=str(w))
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--churn-capacity", "--subs", str(n_resident)],
            capture_output=True, text=True, env=env, timeout=1800,
        )
        if r.returncode != 0:
            log(f"worker={w} run failed:\n{r.stderr[-2000:]}")
            raise SystemExit(1)
        sys.stderr.write(r.stderr)
        rows.append(json.loads(r.stdout.strip().splitlines()[-1]))
    _update_churn_table(rows, os.cpu_count() or 1)
    return rows


def run_retained(n_names=100_000, n_filters=240,
                 batch_sizes=(1, 16, 64, 256)):
    """Retained-index lookup (ISSUE 7 tentpole): subscribe-time wildcard
    fan-in over n_names stored topic names — host trie walk vs the
    BUCKETED device index (`models/retained.py`: per-shape masked-hash
    keys, batched packed probes, host tail scan), exact parity enforced
    per filter.  Sweeps the lookup batch size: the dispatch amortizes
    across concurrent subscribes the way publish ticks amortize
    matching, so lookups/s is a function of B.  Also reports the
    transfer-free kernel rate (the probe dispatch on resident arrays,
    no staging upload / result download) so a slow host<->device link
    can't masquerade as kernel cost.  Reference path:
    `emqx_retainer_mnesia.erl` indexed per-subscribe read.
    """
    dev = init_device()
    import jax

    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.retainer import Retainer
    from emqx_tpu.models.retained import RetainedDeviceIndex

    rng = random.Random(77)
    names = [
        f"dev/{i % 997}/{rng.choice(['t', 'h', 'a'])}/{i % 89}/s/{i}"
        for i in range(n_names)
    ]
    host = Retainer()
    for t in names:
        host.on_publish(Message(topic=t, payload=b"r", retain=True))
    idx = RetainedDeviceIndex(device=dev, cap=_next_pow2_int(n_names))
    ins0 = time.time()
    idx.insert_many(names)
    insert_rps = n_names / (time.time() - ins0)
    third = n_filters // 3
    filters = (
        [f"dev/{rng.randint(0, 996)}/+/{rng.randint(0, 88)}/s/+"
         for _ in range(third)]
        + [f"dev/{rng.randint(0, 996)}/#" for _ in range(third)]
        + [names[rng.randrange(n_names)]
           for _ in range(n_filters - 2 * third)]
    )
    # host trie walk (per filter, like per-subscribe serving)
    t0 = time.time()
    host_hits = sum(len(host.match_filter(f)) for f in filters)
    host_rps = len(filters) / (time.time() - t0)
    # exact parity, every filter (warms shapes + jit variants too)
    trie_served = 0
    res = idx.lookup_batch(filters)
    for f, got in zip(filters, res):
        want = sorted(m.topic for m in host.iter_filter(f))
        if got is None:
            trie_served += 1
            continue
        assert sorted(got) == want, f
    # batch-size sweep; one untimed pass first so the ragged last
    # chunk's jit variants (slice rows) compile outside the window
    batch_rows = []
    for B in batch_sizes:
        chunks = [filters[i:i + B] for i in range(0, len(filters), B)]
        for ch in chunks:
            idx.lookup_batch(ch)
        t0 = time.time()
        n_done = 0
        for _ in range(2):
            for ch in chunks:
                idx.lookup_batch(ch)
                n_done += len(ch)
        batch_rows.append({
            "batch": B,
            "dev_rps": n_done / (time.time() - t0),
        })
    dev_rps = max(r["dev_rps"] for r in batch_rows)
    # transfer-free kernel rate: the probe dispatch alone on resident
    # arrays (one pre-staged [B, 8] query, B=max batch)
    from emqx_tpu.models.retained import _retained_probe

    B = batch_sizes[-1]
    pend = idx.lookup_submit(filters[:B])
    q = jax.device_put(
        np.zeros((_next_pow2_int(max(B, idx.min_batch)), 8),
                 dtype=np.uint32), dev
    )
    idx.lookup_collect(pend)
    darrs = idx._sync()
    kc = idx._kcap_dyn
    _retained_probe(*darrs, q, kcap=kc)[0].block_until_ready()
    KITERS = 30
    t0 = time.time()
    for _ in range(KITERS):
        top, counts = _retained_probe(*darrs, q, kcap=kc)
    jax.block_until_ready((top, counts))
    kernel_rps = KITERS * B / (time.time() - t0)
    # which path does the arbitrated retainer pick on THIS rig?  Attach
    # the index to the populated trie and serve batched rounds; probes
    # re-measure the loser, flips are free to happen either way.
    host.index = idx
    host.probe_interval = 0.02
    for r in range(40):
        fs = [filters[(16 * r + j) % len(filters)] for j in range(16)]
        for m in host.iter_matching(fs):
            pass
        time.sleep(0.001)
    arb = {
        "index": host.index_serves,
        "trie": host.trie_serves,
        "flips": host.path_flips,
        "final": host._last_path,
        "rate_index": host.rate_index,
        "rate_trie": host.rate_trie,
    }
    log(f"retained {n_names:,}: host {host_rps:,.1f} lookups/s, device "
        + "  ".join(f"B={r['batch']} {r['dev_rps']:,.1f}/s"
                    for r in batch_rows)
        + f", kernel {kernel_rps:,.0f}/s ({host_hits} hits, "
        f"{trie_served} trie-served), arbiter index={arb['index']} "
        f"trie={arb['trie']} final={arb['final']}")
    return {
        "n_names": n_names,
        "host_rps": host_rps,
        "dev_rps": dev_rps,
        "kernel_rps": kernel_rps,
        "batch_rows": batch_rows,
        "insert_rps": insert_rps,
        "hits": host_hits,
        "trie_served_filters": trie_served,
        "arb_index": arb["index"],
        "arb_trie": arb["trie"],
        "arb": arb,
        "collisions": idx.collision_count,
        "shapes": idx.shape_count,
        "entries": idx.entry_count,
    }


def run_retained_sweep(populations=(100_000, 1_000_000)):
    """`--retained`: the stored-names x batch-size sweep (BENCH_TABLE
    retained section)."""
    rows = [run_retained(n_names=n) for n in populations]
    return {"populations": rows,
            "n_names": rows[0]["n_names"],
            "host_rps": rows[0]["host_rps"],
            "dev_rps": rows[0]["dev_rps"]}


SEM_WORDS = ("gps position update fix sensor temp battery door kitchen "
             "garage motion alert vibration humidity level tank pump "
             "flow pressure valve open closed status heartbeat firmware "
             "leak smoke siren window freezer boiler solar meter grid "
             "charge drain spin torque axis belt feeder hopper").split()


def _sem_text(rng, n_words=4, tag=None):
    t = " ".join(rng.choice(SEM_WORDS) for _ in range(n_words))
    return t if tag is None else f"{t} {tag}"


def run_semantic(n_queries_sweep=(256, 1024, 4096),
                 batch_sizes=(1, 16, 64, 256), n_texts=512):
    """`--semantic`: the semantic subscription plane (ISSUE 20
    tentpole) — `$semantic/<query>` filters matched on payload meaning
    via device top-k cosine NOMINATION + exact host membership
    (`semantic/engine.py`), against the all-host dense scorer it
    arbitrates with.  Sweeps query-table population x publish batch
    size, reports the transfer-free kernel rate (the `semantic_topk`
    dispatch on resident arrays) so link cost can't masquerade as
    kernel cost, and lets the EWMA arbiter pick a winner on THIS rig.
    Then one e2e leg through the shm hub: a worker-side SemanticPlane
    shipping embed prefixes over a REAL K_SEM ring to a hub-owned
    engine and fanning the K_SEM_RES sections back out — the
    worker never allocates an embedding table.
    """
    dev = init_device()
    import jax

    from emqx_tpu.ops.match import semantic_topk
    from emqx_tpu.semantic.embedder import embed_batch
    from emqx_tpu.semantic.engine import SemanticEngine

    rng = random.Random(1207)
    pops = []
    for nq in n_queries_sweep:
        eng = SemanticEngine(dim=256, max_queries=_next_pow2_int(nq),
                             topk=8, probe_interval=1e9)
        for i in range(nq):
            eng.add_query(_sem_text(rng, 3, tag=f"q{i}"))
        texts = [_sem_text(rng) for _ in range(n_texts)]
        # all-host dense scorer (the arbiter's other arm), B=64
        chunks = [texts[i:i + 64] for i in range(0, len(texts), 64)]
        t0 = time.time()
        n_done = sum(len(ch) for ch in chunks for _ in (eng.match_exact(ch),))
        host_rps = n_done / (time.time() - t0)
        # forced device path, swept over batch size; one untimed pass
        # first so each (B, kcap) jit variant compiles off the clock
        eng.rate_dev, eng.rate_host = 1e9, 1.0
        eng._last_host_meas = time.monotonic()
        batch_rows = []
        for B in batch_sizes:
            chunks = [texts[i:i + B] for i in range(0, len(texts), B)]
            for ch in chunks:
                eng.match(ch)
            eng._last_host_meas = time.monotonic()
            t0 = time.time()
            n_done = 0
            for _ in range(2):
                for ch in chunks:
                    eng.match(ch)
                    n_done += len(ch)
            batch_rows.append({
                "batch": B,
                "dev_rps": n_done / (time.time() - t0),
            })
        dev_rps = max(r["dev_rps"] for r in batch_rows)
        # transfer-free kernel rate: the top-k dispatch on resident
        # arrays (table already device-side, one pre-staged batch)
        B = batch_sizes[-1]
        buf = np.zeros((_next_pow2_int(B), eng.table.dim), np.float32)
        embed_batch(texts[:B], eng.table.dim, out=buf)
        dvecs, dvalid = eng.table.device_tables()
        q = jax.device_put(buf, dev)
        kc = eng._kcap_dyn
        semantic_topk(dvecs, dvalid, q, kcap=kc)[0].block_until_ready()
        KITERS = 30
        t0 = time.time()
        for _ in range(KITERS):
            top = semantic_topk(dvecs, dvalid, q, kcap=kc)
        jax.block_until_ready(top)
        kernel_rps = KITERS * B / (time.time() - t0)
        # arbiter verdict on THIS rig: cold rates, probes allowed
        eng.rate_dev = eng.rate_host = None
        eng._last_path = None
        eng.probe_interval = 0.02
        d0, h0, f0 = eng.matches_dev, eng.matches_host, eng.path_flips
        for r in range(40):
            eng.match([texts[(16 * r + j) % len(texts)]
                       for j in range(16)])
            time.sleep(0.001)
        arb = {
            "device": eng.matches_dev - d0,
            "host": eng.matches_host - h0,
            "flips": eng.path_flips - f0,
            "final": "device" if eng._last_path else "host",
        }
        log(f"semantic {nq:,} queries: host dense {host_rps:,.1f}/s, "
            + "device "
            + "  ".join(f"B={r['batch']} {r['dev_rps']:,.1f}/s"
                        for r in batch_rows)
            + f", kernel {kernel_rps:,.0f}/s, refetches "
            f"{eng.refetches}, arbiter device={arb['device']} "
            f"host={arb['host']} final={arb['final']}")
        pops.append({
            "n_queries": nq,
            "host_rps": host_rps,
            "dev_rps": dev_rps,
            "kernel_rps": kernel_rps,
            "batch_rows": batch_rows,
            "refetches": eng.refetches,
            "arb": arb,
        })
    e2e = _run_semantic_shm_e2e()
    stats = {"populations": pops, "e2e": e2e,
             "n_queries": pops[0]["n_queries"],
             "host_rps": pops[0]["host_rps"],
             "dev_rps": pops[0]["dev_rps"]}
    _update_semantic_table(stats)
    return stats


def _run_semantic_shm_e2e(n_queries=512, ticks=300, batch=16):
    """One lane through a REAL shm ring: worker SemanticPlane submits
    embed prefixes (K_SEM), the hub's engine matches against the ONE
    pool-wide table, per-owner sections ride back (K_SEM_RES) and fan
    out to subscribers — publishes/s and round-trip latency for the
    full worker-visible path."""
    import threading

    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.ops.hashing import HashSpace
    from emqx_tpu.semantic.engine import SemanticEngine
    from emqx_tpu.semantic.plane import SemanticPlane
    from emqx_tpu.shm.client import ShmMatchEngine
    from emqx_tpu.shm.registry import ShmRegistry
    from emqx_tpu.shm.service import MatchService

    rng = random.Random(2026)
    space = HashSpace()
    reg = ShmRegistry(f"sem-bench-{os.getpid()}")
    svc = MatchService(TopicMatchEngine(space=space), reg, slots=64,
                       slot_bytes=65536, poll_interval=0.0005)
    svc.semantic = SemanticEngine(dim=256,
                                  max_queries=_next_pow2_int(n_queries),
                                  topk=8)
    region = svc.create_lane(0)
    db_fd = svc.doorbell_fd(0)
    loop = asyncio.new_event_loop()

    def run_loop():
        asyncio.set_event_loop(loop)
        svc.start()
        loop.run_forever()

    th = threading.Thread(target=run_loop, daemon=True)
    th.start()
    cli = ShmMatchEngine(space=space, region=region, slots=64,
                         slot_bytes=65536, timeout=30.0,
                         doorbell_fd=db_fd)
    cli.sem_node = "bench"
    plane = SemanticPlane(shm=cli, dim=256, topk=8)
    try:
        for i in range(n_queries):
            plane.subscribe(f"c{i}", _sem_text(rng, 3, tag=f"q{i}"))
        deadline = time.time() + 120.0
        while len(cli._qloc2hub) < n_queries:
            cli.poll()
            time.sleep(0.001)
            if time.time() > deadline:
                raise RuntimeError("semantic query acks did not converge")
        payloads = [_sem_text(rng).encode() for _ in range(batch)]

        def tick():
            pend = plane.submit(payloads)
            local, _rem = plane.finish(plane.collect(pend))
            return pend, local

        pend, _ = tick()  # warmup: first hub tick pays any compile
        assert pend is not None and pend.mode == "shm"
        lats = []
        t0 = time.time()
        for _ in range(ticks):
            t1 = time.perf_counter()
            pend, _local = tick()
            lats.append(time.perf_counter() - t1)
        wall = time.time() - t0
        lats.sort()
        degraded = cli.sem_degraded + cli.sem_local
        log(f"semantic e2e (shm hub): {ticks * batch / wall:,.1f} "
            f"publishes/s at B={batch}, tick p50 "
            f"{lats[len(lats) // 2] * 1e6:,.1f}us, degraded {degraded}")
        return {
            "n_queries": n_queries,
            "batch": batch,
            "pub_rps": ticks * batch / wall,
            "tick_p50_us": lats[len(lats) // 2] * 1e6,
            "tick_p99_us": lats[int(len(lats) * 0.99)] * 1e6,
            "degraded": degraded,
            "deliveries": plane.deliveries,
        }
    finally:
        fut = asyncio.run_coroutine_threadsafe(svc.stop(), loop)
        try:
            fut.result(10)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        th.join(10)
        cli.close()
        svc.close()
        loop.close()


SEMANTIC_HEADER = "## Semantic subscriptions ($semantic/<query> through the hub)"


def _update_semantic_table(s: dict) -> None:
    """Write the semantic-bench rows into BENCH_TABLE.md, replacing any
    previous run's section (`--semantic` / `make semantic-bench` owns
    only this section — the restore-table discipline)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == SEMANTIC_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    e = s["e2e"]
    out += [
        "",
        SEMANTIC_HEADER,
        "",
        "Meaning-match over the device-resident query table "
        "(`semantic/engine.py`): feature-hash embeddings, device top-k "
        "cosine NOMINATION under an adaptive kcap, exact host "
        "membership — bit-identical to the dense host scorer by "
        "construction, refetch-on-overflow.  Swept over query-table "
        "population x publish batch size by `python bench.py "
        "--semantic` (`make semantic-bench`); `kernel/s` is the "
        "transfer-free top-k dispatch on resident arrays; `arbiter` is "
        "the EWMA rate arbiter's device/host serve split (and final "
        "pick) with probes on, cold rates, on this rig.",
        "",
        "| queries | host dense/s | "
        + " | ".join(f"device B={r['batch']}/s"
                     for r in s["populations"][0]["batch_rows"])
        + " | kernel/s | arbiter dev/host (final) |",
        "|---|---|" + "---|" * len(s["populations"][0]["batch_rows"])
        + "---|---|",
    ]
    for p in s["populations"]:
        out.append(
            f"| {p['n_queries']:,} | {p['host_rps']:,.1f} | "
            + " | ".join(f"{r['dev_rps']:,.1f}" for r in p["batch_rows"])
            + f" | {p['kernel_rps']:,.0f} "
            f"| {p['arb']['device']}/{p['arb']['host']} "
            f"({p['arb']['final']}) |"
        )
    out += [
        "",
        f"E2e through the shm hub (one worker lane, REAL K_SEM rings, "
        f"{e['n_queries']:,} pool queries, worker holds NO embedding "
        f"table): **{e['pub_rps']:,.1f} publishes/s** at "
        f"B={e['batch']}, round-trip p50 {e['tick_p50_us']:,.1f}us / "
        f"p99 {e['tick_p99_us']:,.1f}us, {e['degraded']} degraded "
        f"ticks.",
        "",
    ]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md semantic section")


def run_restore(n=100_000, wal_tail=2_000):
    """Warm-restart bench (`checkpoint/`): snapshot+WAL restore vs the
    cold rebuild a session-file boot pays.

    * rebuild — the CURRENT boot path: `broker/persist.py restore()`
      replays each parked session's subscriptions through
      `broker.subscribe` -> per-filter `engine.add_filter` (sessions
      hold a handful of filters each, so the >=512 bulk fast path never
      engages), then one device sync;
    * bulk    — the best-case cold rebuild (ONE `add_filters` batch +
      sync), reported so the gate can't hide behind a strawman;
    * restore — newest snapshot adoption + a `wal_tail`-op churn-WAL
      tail replay + the same one-shot device sync.

    All three end with identical host truth AND a synced mirror, parity-
    checked before any number is reported.  Runs on the CPU backend —
    the work under test is host-truth reconstruction; the device upload
    is one bulk transfer on every side.  Acceptance (ISSUE 3): restore
    >= 5x faster than the boot-path rebuild at 100k filters.
    """
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from emqx_tpu.checkpoint.manager import CheckpointManager
    from emqx_tpu.models.engine import TopicMatchEngine

    rng = random.Random(4242)
    filters, _ = pop_wild_100k(rng, n)
    tail_adds = [f"restore/tail/{i}/+" for i in range(wal_tail)]
    all_filters = filters + tail_adds
    tmp = tempfile.mkdtemp(prefix="ckpt-bench-")
    try:
        # source engine: populate, snapshot, then churn a WAL tail
        src = TopicMatchEngine()
        mgr = CheckpointManager(src, tmp)
        src.add_filters(filters)
        mgr.checkpoint()
        src.apply_churn(tail_adds, [])
        log(f"source: {src.n_filters:,} filters snapshotted + "
            f"{wal_tail:,}-op WAL tail "
            f"({mgr.wal.pending_bytes():,} B pending)")

        import gc

        # warm restore first (snapshot adoption + WAL replay + one bulk
        # sync), then the cold rebuilds — the per-filter boot loop below
        # allocates millions of objects whose GC pressure would
        # otherwise bleed into the restore timing
        gc.collect()
        warm = TopicMatchEngine()
        mgr2 = CheckpointManager(warm, tmp)
        t0 = time.time()
        n_restored = mgr2.restore()
        jax.block_until_ready(tuple(warm.sync_device()))
        restore_ms = (time.time() - t0) * 1e3

        # cold rebuild, best case: one bulk add_filters
        gc.collect()
        bulk = TopicMatchEngine()
        bulk.add_filter("$bench/warm")  # lib/registry first-call setup
        bulk.remove_filter("$bench/warm")
        t0 = time.time()
        bulk.add_filters(all_filters)
        jax.block_until_ready(tuple(bulk.sync_device()))
        bulk_ms = (time.time() - t0) * 1e3

        # cold rebuild, boot path: per-filter inserts (session restore)
        gc.collect()
        cold = TopicMatchEngine()
        cold.add_filter("$bench/warm")
        cold.remove_filter("$bench/warm")
        t0 = time.time()
        for f in all_filters:
            cold.add_filter(f)
        jax.block_until_ready(tuple(cold.sync_device()))
        rebuild_ms = (time.time() - t0) * 1e3

        assert n_restored == cold.n_filters == src.n_filters, (
            n_restored, cold.n_filters, src.n_filters)
        sample = [f"device/{i}/temp/{i % 100}/raw/{i % 4096}"
                  for i in range(0, 1000, 7)] + ["restore/tail/5/x"]
        mc = [sorted(s) for s in cold.match(sample)]
        mw = [sorted(s) for s in warm.match(sample)]
        assert mc == mw, "restored engine diverges from cold rebuild"
        speedup = rebuild_ms / max(restore_ms, 1e-9)
        log(f"cold rebuild {rebuild_ms:,.1f} ms (boot path, per-filter; "
            f"bulk best case {bulk_ms:,.1f} ms), snapshot+WAL restore "
            f"{restore_ms:,.1f} ms -> {speedup:.1f}x vs boot, "
            f"{bulk_ms / max(restore_ms, 1e-9):.1f}x vs bulk "
            f"({n_restored:,} filters, match parity on "
            f"{len(sample)} topics)")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    stats = {
        "n_filters": n_restored,
        "wal_tail_ops": wal_tail,
        "rebuild_ms": rebuild_ms,
        "bulk_ms": bulk_ms,
        "restore_ms": restore_ms,
        "speedup": speedup,
        "speedup_vs_bulk": bulk_ms / max(restore_ms, 1e-9),
    }
    _update_restore_table(stats)
    return stats


RESTORE_HEADER = "## Restore vs cold rebuild (table checkpoint + churn WAL)"


def _update_restore_table(s: dict) -> None:
    """Write the restore-bench row into BENCH_TABLE.md, replacing any
    previous run's section (the full `bench.py` run rewrites the file
    wholesale; `--restore` / `make restore-bench` owns only this
    section)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == RESTORE_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    out += [
        "",
        RESTORE_HEADER,
        "",
        "Warm restart (`checkpoint/`: newest snapshot adoption + churn-"
        "WAL tail replay + ONE bulk device upload) vs the cold boot "
        "path (`broker/persist.py restore()` replays each session's "
        "subscriptions per filter through `engine.add_filter` — "
        "sessions hold a handful of filters each, so the bulk fast "
        "path never engages), with the best-case ONE-batch "
        "`add_filters` rebuild alongside so the gate is not a strawman. "
        " Measured by `python bench.py --restore` (`make "
        "restore-bench`) on the CPU backend — the work under test is "
        "host-truth reconstruction; the device upload is one bulk "
        "transfer on every side.  The restore side replays a "
        f"{s['wal_tail_ops']:,}-op WAL tail, and all sides are "
        "match-parity-checked before timing is reported.",
        "",
        "| filters | wal tail ops | rebuild_ms (boot path) "
        "| bulk add_filters ms | restore_ms | restore vs boot "
        "| restore vs bulk |",
        "|---|---|---|---|---|---|---|",
        f"| {s['n_filters']:,} | {s['wal_tail_ops']:,} "
        f"| {s['rebuild_ms']:,.1f} | {s['bulk_ms']:,.1f} "
        f"| {s['restore_ms']:,.1f} | {s['speedup']:.1f}x "
        f"| {s['speedup_vs_bulk']:.1f}x |",
        "",
    ]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md restore section")


def run_ds(n_sessions=500, n_msgs=100):
    """Offline-fanout replay bench (`ds/`): N parked persistent sessions
    x M QoS1 offline messages, durable-log cursors vs the legacy
    per-session JSON snapshot path.

    Measures, per side:
      * park_tick_ms  — steady-state housekeeping cost with all offline
        traffic landed: legacy rewrites every dirty session's full
        mqueue JSON (O(sessions x queue depth)); ds fsyncs the
        coalesced log tail (O(bytes), and the session files are static);
      * restore_ms    — boot-path store load (legacy parses N x M
        messages; ds parses N cursor records);
      * resume_ms     — first session resume after boot (legacy: the
        mqueue came with the file; ds: replay M messages from the log);
      * resume_total_ms = restore + resume — the reconnecting client's
        actual wait, the acceptance gate's "resume latency".

    Both sides end with the resumed session holding exactly M messages
    (parity-checked before any number is reported).  Runs on the CPU
    backend — the work under test is host-side durability IO.
    """
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.packet import SubOpts
    from emqx_tpu.broker.persist import DiscBackend, SessionPersistence
    from emqx_tpu.broker.session import Session
    from emqx_tpu.config.config import Config
    from emqx_tpu.ds.manager import DsManager

    def park_all(b, p):
        for i in range(n_sessions):
            cid = f"park-{i}"
            s = Session(clientid=cid, expiry_interval=3600,
                        max_mqueue=0)
            s.subscriptions["bench/ds/#"] = SubOpts(qos=1)
            b.subscribe(cid, "bench/ds/#", SubOpts(qos=1))
            b.cm.pending[cid] = (s, float("inf"))
            p._on_park(cid, s, float("inf"))

    def publish_all(b):
        msgs = [
            Message(topic=f"bench/ds/{i % 8}",
                    payload=f"offline-{i:05d}".encode(), qos=1)
            for i in range(n_msgs)
        ]
        for i in range(0, len(msgs), 64):
            b.publish_many(msgs[i:i + 64])

    def ds_mgr(b, d):
        conf = Config({"ds": {"enable": True, "shards": 4,
                              "flush_bytes": 1 << 30}})  # tick-driven
        mgr = DsManager(b, os.path.join(d, "ds"), conf,
                        metrics=b.metrics)
        b.ds = mgr
        return mgr

    out = {}
    for mode in ("legacy", "ds"):
        d = tempfile.mkdtemp(prefix=f"ds-bench-{mode}-")
        try:
            b = Broker()
            mgr = ds_mgr(b, d) if mode == "ds" else None
            p = SessionPersistence(b, DiscBackend(
                os.path.join(d, "sess")))
            park_all(b, p)
            publish_all(b)
            # steady-state park tick: everything offline-queued, flush
            t0 = time.time()
            p.tick()
            if mgr is not None:
                mgr.tick(now=1e18)  # force the interval flush + GC
            park_tick_ms = (time.time() - t0) * 1e3
            if mgr is not None:
                mgr.close()

            # boot: fresh broker restores the store
            b2 = Broker()
            mgr2 = ds_mgr(b2, d) if mode == "ds" else None
            p2 = SessionPersistence(b2, DiscBackend(
                os.path.join(d, "sess")))
            t0 = time.time()
            n_restored = p2.restore()
            restore_ms = (time.time() - t0) * 1e3
            assert n_restored == n_sessions, (mode, n_restored)

            # first resume: the reconnecting client's replay
            t0 = time.time()
            s, present = b2.cm.open_session(
                False, "park-0", lambda: Session(clientid="park-0"))
            resume_ms = (time.time() - t0) * 1e3
            assert present, mode
            got = len(s.mqueue) + len(s.inflight)
            assert got == n_msgs, (mode, got, n_msgs)
            if mgr2 is not None:
                mgr2.close()
            out[mode] = {
                "park_tick_ms": park_tick_ms,
                "restore_ms": restore_ms,
                "resume_ms": resume_ms,
                "resume_total_ms": restore_ms + resume_ms,
            }
            log(f"{mode}: park-tick {park_tick_ms:,.1f} ms, "
                f"restore {restore_ms:,.1f} ms, "
                f"resume {resume_ms:,.1f} ms")
        finally:
            shutil.rmtree(d, ignore_errors=True)
    stats = {
        "n_sessions": n_sessions,
        "n_msgs": n_msgs,
        "legacy": out["legacy"],
        "ds": out["ds"],
        "park_tick_speedup":
            out["legacy"]["park_tick_ms"]
            / max(out["ds"]["park_tick_ms"], 1e-9),
        "resume_speedup":
            out["legacy"]["resume_total_ms"]
            / max(out["ds"]["resume_total_ms"], 1e-9),
    }
    log(f"offline fanout ({n_sessions} sessions x {n_msgs} msgs): "
        f"park-tick {stats['park_tick_speedup']:.1f}x, "
        f"resume {stats['resume_speedup']:.1f}x vs legacy snapshots")
    _update_ds_table(stats)
    return stats


DS_HEADER = "## Durable message log (offline-fanout replay)"


def _update_ds_table(s: dict) -> None:
    """Write the ds-bench section into BENCH_TABLE.md, replacing any
    previous run's (same ownership contract as the restore section)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == DS_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    leg, ds = s["legacy"], s["ds"]
    out += [
        "",
        DS_HEADER,
        "",
        "N parked persistent sessions x M QoS1 offline messages "
        "(fanout: every message matches every session).  `legacy` = "
        "per-session JSON mqueue snapshots (`broker/persist.py`), "
        "re-written whole on every housekeeping tick; `ds` = the "
        "shared durable log (`emqx_tpu/ds/`): one append per message, "
        "static cursor-form session files, mqueue rebuilt by cursor "
        "replay on resume.  park-tick = steady-state flush cost with "
        "all offline traffic landed; resume = boot restore + first "
        "session resume (the reconnecting client's wait).  Measured "
        "by `python bench.py --ds` (`make ds-bench`) on the CPU "
        "backend — the work under test is host-side durability IO.",
        "",
        "| sessions | offline msgs | metric | legacy | ds | speedup |",
        "|---|---|---|---|---|---|",
        f"| {s['n_sessions']:,} | {s['n_msgs']:,} | park-tick ms "
        f"| {leg['park_tick_ms']:,.1f} | {ds['park_tick_ms']:,.1f} "
        f"| {s['park_tick_speedup']:.1f}x |",
        f"| {s['n_sessions']:,} | {s['n_msgs']:,} | restore ms "
        f"| {leg['restore_ms']:,.1f} | {ds['restore_ms']:,.1f} "
        f"| {leg['restore_ms'] / max(ds['restore_ms'], 1e-9):.1f}x |",
        f"| {s['n_sessions']:,} | {s['n_msgs']:,} | resume ms "
        "(restore + replay) "
        f"| {leg['resume_total_ms']:,.1f} "
        f"| {ds['resume_total_ms']:,.1f} "
        f"| {s['resume_speedup']:.1f}x |",
        "",
    ]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md durable-message-log section")


def run_takeover(n_msgs=10_000, reps=3):
    """Cross-node takeover of a parked session with a deep offline
    queue: materialized session ship vs the replicated-mirror cursor
    handoff (`ds/repl.py` + session_takeover v2).

    Per mode, a two-node loopback cluster parks one persistent session
    on the origin, lands `n_msgs` QoS1 messages in its durable log,
    then the taker runs the real `_query_takeover` RPC and resumes:

      * materialized — no replication plane: the origin replays the log
        into the mqueue and ships every message inside the RPC response
        (the pre-repl path, still the fallback);
      * handoff — both nodes run DsReplicator, replication caught up:
        the response is the session record + cursor, the queue is
        rebuilt locally from the taker's mirror.

    Reports the RPC response size (bytes on the wire) and the
    end-to-end takeover latency (query -> resumed mqueue holding all
    `n_msgs`), median of `reps` with rep spread.  Parity: both modes
    must end with exactly `n_msgs` messages queued.
    """
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.packet import SubOpts
    from emqx_tpu.broker.persist import (
        SessionPersistence,
        session_from_dict,
    )
    from emqx_tpu.broker.session import Session
    from emqx_tpu.cluster import ClusterBroker, ClusterNode
    from emqx_tpu.config.config import Config
    from emqx_tpu.ds.manager import DsManager
    from emqx_tpu.ds.repl import DsReplicator

    conf_raw = {"enable": True, "shards": 4, "flush_bytes": 1 << 30,
                "repl.enable": True, "repl.ack_timeout": 5.0,
                "repl.retry_interval": 0.1}

    async def wait_until(pred, timeout=30.0):
        deadline = time.monotonic() + timeout
        while not pred():
            if time.monotonic() > deadline:
                raise RuntimeError("takeover bench condition timeout")
            await asyncio.sleep(0.01)

    async def one_rep(d, with_repl):
        nodes, repls = [], []
        for name in ("tb-a", "tb-b"):
            b = ClusterBroker()
            conf = Config({"ds": dict(conf_raw)})
            ds = DsManager(b, os.path.join(d, name, "ds"), conf,
                           metrics=b.metrics)
            b.ds = ds
            SessionPersistence(b)
            node = ClusterNode(name, b, heartbeat_ivl=0.2)
            repl = DsReplicator(node, ds, conf, metrics=b.metrics) \
                if with_repl else None
            await node.start()
            if repl is not None:
                repl.start()
            nodes.append(node)
            repls.append(repl)
        na, nb = nodes
        try:
            na.join("tb-b", ("127.0.0.1", nb.transport.port))
            nb.join("tb-a", ("127.0.0.1", na.transport.port))
            await wait_until(lambda: "tb-b" in na.up_peers()
                             and "tb-a" in nb.up_peers())
            # park one persistent session on A, then land the queue
            cid = "takeover-bench"
            s = Session(clientid=cid, expiry_interval=3600,
                        max_mqueue=0)
            s.subscriptions["bench/to/#"] = SubOpts(qos=1)
            na.broker.subscribe(cid, "bench/to/#", SubOpts(qos=1))
            na.broker.cm.pending[cid] = (s, float("inf"))
            na.broker.persistence._on_park(cid, s, float("inf"))
            msgs = [
                Message(topic=f"bench/to/{i % 8}",
                        payload=f"offline-{i:06d}-{'x' * 48}".encode(),
                        qos=1)
                for i in range(n_msgs)
            ]
            for i in range(0, len(msgs), 256):
                na.broker.publish_many(msgs[i:i + 256])
            na.broker.ds.flush_all()
            if with_repl:
                await wait_until(lambda: repls[0].lag() == 0)

            # the measured leg: real RPC query -> local resume replay
            t0 = time.perf_counter()
            resp = await nb._query_takeover(cid)
            assert resp is not None and resp.get("found")
            wire_bytes = len(json.dumps(
                resp, separators=(",", ":")).encode())
            data = resp["session"]
            session = session_from_dict(data)
            if resp.get("handoff"):
                origin = data.get("cursor_node") or ""
                tail = {int(k): v
                        for k, v in (resp.get("tail") or {}).items()}
                if nb.ds_repl is not None and tail:
                    tail = nb.ds_repl.absorb_tail(origin, tail)
                session.ds_handoff_tail = tail or None
            nb.broker.cm.pending[cid] = (session, float("inf"))
            nb.broker.ds.replay_into(session)
            takeover_ms = (time.perf_counter() - t0) * 1e3
            got = len(session.mqueue) + len(session.inflight)
            assert got == n_msgs, (with_repl, got, n_msgs)
            assert bool(resp.get("handoff")) == with_repl
            return wire_bytes, takeover_ms
        finally:
            for repl in repls:
                if repl is not None:
                    await repl.stop()
            for node in nodes:
                await node.stop()
                node.broker.ds.close()

    out = {}
    for mode, with_repl in (("materialized", False), ("handoff", True)):
        byts, times = [], []
        for _rep in range(reps):
            d = tempfile.mkdtemp(prefix=f"takeover-{mode}-")
            try:
                wb, ms = asyncio.run(one_rep(d, with_repl))
            finally:
                shutil.rmtree(d, ignore_errors=True)
            byts.append(wb)
            times.append(ms)
        times.sort()
        out[mode] = {
            "wire_bytes": int(statistics.median(byts)),
            "takeover_ms": statistics.median(times),
            "spread_ms": times[-1] - times[0],
        }
        log(f"{mode}: {out[mode]['wire_bytes']:,} B on the wire, "
            f"takeover {out[mode]['takeover_ms']:,.1f} ms "
            f"(spread {out[mode]['spread_ms']:,.1f})")
    stats = {
        "n_msgs": n_msgs,
        "reps": reps,
        "materialized": out["materialized"],
        "handoff": out["handoff"],
        "bytes_reduction":
            out["materialized"]["wire_bytes"]
            / max(out["handoff"]["wire_bytes"], 1),
        "latency_speedup":
            out["materialized"]["takeover_ms"]
            / max(out["handoff"]["takeover_ms"], 1e-9),
    }
    log(f"takeover ({n_msgs:,}-message parked queue): "
        f"{stats['bytes_reduction']:,.0f}x fewer bytes shipped, "
        f"{stats['latency_speedup']:.1f}x takeover latency vs "
        f"materialization")
    _update_takeover_table(stats)
    return stats


TAKEOVER_HEADER = "## Cross-node takeover (cursor handoff vs " \
    "materialized queue)"


def _update_takeover_table(s: dict) -> None:
    """Write the takeover-bench section into BENCH_TABLE.md, replacing
    any previous run's (same ownership contract as the ds section)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == TAKEOVER_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    mat, ho = s["materialized"], s["handoff"]
    out += [
        "",
        TAKEOVER_HEADER,
        "",
        "One parked persistent session holding a "
        f"{s['n_msgs']:,}-message QoS1 offline queue crosses nodes "
        "over the real `session_takeover` RPC.  `materialized` = the "
        "origin replays its durable log into the mqueue and ships "
        "every message in the response (the pre-replication path, "
        "still the fallback); `handoff` = both nodes run the ds "
        "replication plane (`ds/repl.py`), the response carries only "
        "the session record + cursor, and the taker rebuilds the "
        "queue from its local mirror.  takeover = query -> resumed "
        "mqueue holding every message, median of "
        f"{s['reps']} reps (spread = max-min).  Measured by "
        "`python bench.py --takeover` (`make takeover-bench`) on the "
        "CPU backend.",
        "",
        "| parked msgs | metric | materialized | handoff | gain |",
        "|---|---|---|---|---|",
        f"| {s['n_msgs']:,} | RPC response bytes "
        f"| {mat['wire_bytes']:,} | {ho['wire_bytes']:,} "
        f"| {s['bytes_reduction']:,.0f}x fewer |",
        f"| {s['n_msgs']:,} | takeover ms "
        f"| {mat['takeover_ms']:,.1f} (±{mat['spread_ms']:,.1f}) "
        f"| {ho['takeover_ms']:,.1f} (±{ho['spread_ms']:,.1f}) "
        f"| {s['latency_speedup']:.1f}x |",
        "",
    ]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md takeover section")


def _next_pow2_int(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def dispatch_expansion_rate(n: int) -> float:
    """Host-side fan-out dispatch cost (match excluded): one filter with
    N subscribers, measure deliveries/s through the vectorized
    SubscriberShards expansion (`emqx_broker.erl:499-524` hot loop)."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.packet import SubOpts

    class _Sink:
        __slots__ = ("clientid",)

        def __init__(self, cid):
            self.clientid = cid

        def deliver(self, delivers):
            pass

        def kick(self, rc):
            pass

    b = Broker()
    for i in range(n):
        cid = f"d{i}"
        b.cm.channels[cid] = _Sink(cid)
        b.subscribe(cid, "wide/t", SubOpts(qos=0))
    fid = b.engine.fid_of("wide/t")
    msg = Message(topic="wide/t", payload=b"x")
    iters = max(2, 200_000 // n)
    b._dispatch(msg, {fid})  # warm
    t0 = time.time()
    for _ in range(iters):
        b._dispatch(msg, {fid})
    return iters * n / (time.time() - t0)


FANOUT_SWEEP = (1_000, 10_000, 50_000, 100_000)
FANOUT_GATE_N = 50_000
# wire deliveries/s at 50k subscribers before the delivery-plane rework
# (PR 9); the --fanout gate is >= 2x this row
FANOUT_BASELINE_50K = 90_279.0


def run_fanout(reps: int = 3):
    """Delivery-plane fan-out sweep: one filter, N subscribers, both
    legs per population — `expansion` (broker fid->receivers through
    SubscriberShards, delivery callback empty) and `wire` (the FULL
    channel path: scatter lane, shared packet prefix, per-receiver
    serialize_cached).  Per-row rate = median of `reps` runs."""
    rows = []
    for n in FANOUT_SWEEP:
        log(f"fanout sweep: {n:,} subscribers")
        exp = dispatch_expansion_rate(n)
        wire_reps = sorted(wire_fanout_rate(n) for _ in range(reps))
        wire = wire_reps[len(wire_reps) // 2]
        rows.append({
            "subscribers": n,
            "expansion_rps": exp,
            "wire_rps": wire,
            "per_delivery_ns": 1e9 / wire,
            "expansion_vs_wire": exp / wire,
            "wire_reps": [round(r, 1) for r in wire_reps],
        })
    per_ns = {r["subscribers"]: r["per_delivery_ns"] for r in rows}
    gate = next(r for r in rows if r["subscribers"] == FANOUT_GATE_N)
    stats = {
        "rows": rows,
        "wire_rps_50k": gate["wire_rps"],
        "vs_pre_rework_50k": gate["wire_rps"] / FANOUT_BASELINE_50K,
        # cache-resident 1k is the outlier; report both spans honestly
        "flat_ratio_1k_100k": per_ns[100_000] / per_ns[1_000],
        "flat_ratio_10k_100k": per_ns[100_000] / per_ns[10_000],
    }
    from emqx_tpu.broker import frame as framelib

    stats["prefix_cache"] = dict(framelib.PREFIX_STATS)
    return stats


MESH_HEADER_PREFIX = "## Mesh-sharded engine"
PREP_HEADER = "## Fused prep op (microbench)"


def _mesh_section_lines(sharded_rows: dict, single: dict = None) -> list:
    """The BENCH_TABLE.md mesh section (shared by the --all writer and
    the --sharded marker update).  `sharded_rows`: workload -> stats
    JSON from run_sharded; `single`: optional single-chip config-2
    stats for the comparison row."""
    nd = next(iter(sharded_rows.values()))["n_devices"]
    lines = [
        "",
        f"{MESH_HEADER_PREFIX} (BASELINE workloads, {nd} virtual CPU "
        "devices)",
        "",
        "`broker.engine=sharded` path: fused churn+compact-match "
        "dispatch over the mesh (`sharded_step_compact_packed`), "
        "pipelined through the engine.pipeline_depth in-flight window "
        "with the PR 12 fused native prep op (`etpu_prep_pack`: one "
        "GIL-released split+hash+memo+dedup+pack pass) and the "
        "prep-ahead stage (a persistent worker preps tick N+1..N+depth "
        "while tick N's dispatch is in flight; consecutive prepped "
        "ticks COALESCE into one mesh dispatch, group sizes 1/2/4).  "
        "Exact verification on, tick 512.  One row per (workload, "
        "depth): depth 1 is the lock-step baseline, depth N the "
        "pipelined window; occ = mean flight-recorder occupancy at "
        "submit, prep = mean prep-ahead tickets ready at submit, grp = "
        "mean coalesced-dispatch group size; rep spread = "
        "(max-min)/median over the interleaved reps, the row's own "
        "noise bar.  Workloads 3/5 run at 1M "
        "resident filters (the virtual mesh shares one host's "
        "RAM/cores; w5 pays its 5%/sec churn inside the loop — the "
        "settle window calibrates a FIXED per-tick churn quota at the "
        "measured depth, so measured reps retire identical schedules "
        "instead of the wall-clock pacer's feedback loop, which "
        "spread the old depth legs 8.5k-41k; the CPU baseline pays "
        "the same churn).  Virtual devices "
        "share this host's cores, so these rows measure the sharded "
        "DISPATCH PATH's overhead/correctness at scale, not ICI "
        "speedup.  PR 12 note: the old prep column (7.6-9.1 ms) LUMPED "
        "the synchronous inline portion of the mesh-execute call into "
        "prep — the re-attributed columns below split real prep work "
        "(hash/pack/submit, now fused native) from the dispatch call + "
        "compute, and the coalesced group dispatch is what moves the "
        "depth-4/depth-1 ratio above 1.0 on this 1-hardware-thread "
        "host (per-dispatch overhead amortizes over the group; on real "
        "parallel hardware the overlap win stacks on top).",
        "",
        "| workload | filters | depth | lookups/s | rep spread | "
        "vs cpu | occ | prep | grp | p99 ms | prep ms | "
        "hash/pack/submit | dispatch ms | fetch ms | verify ms | "
        "insert/s | churn/s applied (target) |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|"
        "---|---|",
    ]
    for w, s in sorted(sharded_rows.items()):
        ph = s.get("phases", {})
        churn_col = (
            "%s (%s)" % (
                format(round(s.get("churn_rps", 0)), ","),
                format(round(s.get("churn_target", 0)), ","),
            )
            if s.get("churn_target") else "—"
        )
        sub = (f"{ph.get('prep_hash_ms', 0):.3f}/"
               f"{ph.get('prep_pack_ms', 0):.3f}/"
               f"{ph.get('prep_submit_ms', 0):.3f}")
        for dr in s.get("depth_rows") or [
            {"depth": 3, "rps": s["tpu_rps"], "occ_mean": 0.0}
        ]:
            spread = (
                f"±{dr['rep_spread_pct']:.0f}%"
                if dr.get("rep_spread_pct") is not None else "—"
            )
            lines.append(
                f"| {w}: {CONFIGS[w][1]} | {s['n_filters']:,} "
                f"| {dr['depth']} "
                f"| {dr['rps']:,.0f} "
                f"| {spread} "
                f"| {dr['rps']/s['cpu_rps']:.1f}x "
                f"| {dr['occ_mean']:.1f} "
                f"| {dr.get('prep_occ_mean', 0.0):.1f} "
                f"| {dr.get('group_mean', 1.0):.1f} "
                f"| {s['p99_ms']:.2f} "
                f"| {ph.get('prep_ms', 0):.2f} "
                f"| {sub} "
                f"| {ph.get('dispatch_ms', 0):.2f} "
                f"| {ph.get('fetch_ms', 0):.2f} "
                f"| {ph.get('verify_ms', 0):.2f} "
                f"| {s['insert_rps']:,.0f} "
                f"| {churn_col} |"
            )
    if single is not None:
        lines.append(
            f"| single-chip hybrid (row 2, tick 4096) "
            f"| {single['n_filters']:,} | — "
            f"| {single['tpu_rps']:,.0f} | — "
            f"| {single['tpu_rps']/single['cpu_rps']:.1f}x | — | | "
            f"| {single['p99_ms']:.2f} | | | | | | "
            f"| {single['insert_rps']:,.0f} | |"
        )
    lines.append(
        "\nPhases per 512-topic tick, measured LOCK-STEP so each is "
        "exposed (in the pipelined rows above, dispatch overlaps the "
        "other phases of neighboring ticks): prep = the fused native "
        "prep op only — hash (split+hash+memo+dedup), pack "
        "(staging-buffer gather+pad), submit (group assembly + "
        "device_put handoff) — dispatch = the mesh-execute call + "
        "device compute wait (the call's synchronous inline portion "
        "was previously mis-attributed to prep), fetch = resolve "
        "(live [D, n, k] slice + u16 counts + any overflow refetch), "
        "verify = registry exact-check + row assembly."
    )
    lines.append("")
    return lines


def _stash_path(name: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


def _update_mesh_table(stats: dict) -> None:
    """Merge one --sharded workload's stats into the BENCH_TABLE.md
    mesh section (marker replacement, same ownership contract as the
    fan-out/spans sections).  Per-workload stats stash in
    BENCH_mesh_w<w>.json so a single-workload re-measure keeps the
    other rows; BENCH_mesh_single.json (optional) carries the
    single-chip comparison row."""
    w = int(stats["workload"])
    with open(_stash_path(f"BENCH_mesh_w{w}.json"), "w",
              encoding="utf-8") as f:
        json.dump(stats, f)
    sharded_rows = {}
    for ww in (2, 3, 5):
        p = _stash_path(f"BENCH_mesh_w{ww}.json")
        if os.path.exists(p):
            with open(p, "r", encoding="utf-8") as f:
                sharded_rows[ww] = json.load(f)
    single = None
    sp = _stash_path("BENCH_mesh_single.json")
    if os.path.exists(sp):
        with open(sp, "r", encoding="utf-8") as f:
            single = json.load(f)
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping, replaced = [], False, False
    for line in lines:
        if line.strip().startswith(MESH_HEADER_PREFIX):
            skipping = True
            if not replaced:
                replaced = True
                out.extend(_mesh_section_lines(sharded_rows, single))
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    if not replaced:
        out.extend(_mesh_section_lines(sharded_rows, single))
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    log("updated BENCH_TABLE.md mesh-sharded section")


def run_prep_only(workload: int = 2):
    """Fused-native vs python-fallback prep in ISOLATION: the whole
    prep stage (split+hash+memo+dedup+bucket-pack into the staging
    buffer) timed per tick at B=512 and B=2048 over the sharded
    workload's own topic stream — the op's speedup measured without
    the dispatch path around it (`make prep-bench`)."""
    from emqx_tpu.ops import hashing
    from emqx_tpu.ops import native as _native
    from emqx_tpu.ops.prep import TopicPrep

    rng = random.Random(1236)
    if workload == 2:
        _filters, topics_fn = pop_wild_100k(rng, 10_000)
    else:
        _filters, topics_fn = pop_mixed(rng, 50_000)
    space = hashing.HashSpace()
    rows = []
    for B in (512, 2048):
        batches = []
        while len(batches) < 8:
            t = topics_fn()
            while len(t) < B:
                t = t + topics_fn()
            batches.append(t[:B])
        for mode in ("native", "python"):
            use_native = mode == "native"
            if use_native and not _native.available():
                continue
            prep = TopicPrep(space, min_batch=64, use_native=use_native)
            for b in batches:  # warm the memo (steady-state Zipf serve)
                r = prep.pack(list(b))
                prep.release(r.buf, r.key)
            reps = 50 if use_native else 20
            hash_s = pack_s = 0.0
            t0 = time.perf_counter()
            for i in range(reps):
                r = prep.pack(list(batches[i % 8]))
                hash_s += r.hash_s
                pack_s += r.pack_s
                prep.release(r.buf, r.key)
            dt = time.perf_counter() - t0
            rows.append({
                "B": B, "mode": mode,
                "tick_us": dt / reps * 1e6,
                "hash_us": hash_s / reps * 1e6,
                "pack_us": pack_s / reps * 1e6,
                "topics_per_s": reps * B / dt,
                "memo_hit_rate": prep.hits / max(prep.hits + prep.misses,
                                                 1),
            })
            log(f"prep-only B={B} {mode}: {dt/reps*1e6:,.0f} us/tick "
                f"({reps*B/dt:,.0f} topics/s; hash {hash_s/reps*1e6:,.0f} "
                f"pack {pack_s/reps*1e6:,.0f} us)")
    by = {(r["B"], r["mode"]): r for r in rows}
    speedups = {
        B: by[(B, "python")]["tick_us"] / by[(B, "native")]["tick_us"]
        for B in (512, 2048)
        if (B, "native") in by and (B, "python") in by
    }
    stats = {"rows": rows, "speedups": speedups,
             "workload": workload,
             "pool_width": _pool_width(),
             "host_threads": os.cpu_count() or 1}
    _update_prep_table(stats)
    return stats


def _update_prep_table(s: dict) -> None:
    """Replace the fused-prep microbench section of BENCH_TABLE.md."""
    lines_new = [
        "",
        PREP_HEADER,
        "",
        "The whole prep stage in isolation — split + hash + "
        "two-generation topic memo + in-tick dedup + bucket-padded "
        "[B, 2L+2] staging pack — fused native (`native/prep.cc "
        "etpu_prep_pack`, GIL-released, pool width "
        f"{s['pool_width']}) vs the pure-Python fallback, per 512/2048-"
        "topic tick over the sharded workload's Zipf topic stream "
        "(steady-state memo).  `python bench.py --sharded --prep-only` "
        "(`make prep-bench`).",
        "",
        "| B | path | tick us | hash us | pack us | topics/s | "
        "memo hit rate | native speedup |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in s["rows"]:
        sp = s["speedups"].get(r["B"])
        sp_col = (f"{sp:.1f}x" if sp and r["mode"] == "native" else "")
        lines_new.append(
            f"| {r['B']} | {r['mode']} | {r['tick_us']:,.0f} "
            f"| {r['hash_us']:,.0f} | {r['pack_us']:,.0f} "
            f"| {r['topics_per_s']:,.0f} | {r['memo_hit_rate']:.2f} "
            f"| {sp_col} |"
        )
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == PREP_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    out += lines_new
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    log("updated BENCH_TABLE.md fused-prep section")


FANOUT_HEADER = "## Delivery-plane fan-out"


def _fanout_section_lines(s: dict) -> list:
    lines = [
        "",
        FANOUT_HEADER,
        "",
        "One filter, N subscribers (the broadcast shape; match "
        "excluded).  `expansion` = broker fid->receivers through the "
        "vectorized SubscriberShards layer (delivery callback empty); "
        "`wire` = the FULL channel path per receiver — broadcast "
        "scatter lane (`broker._scatter_one_filter` + per-uid callback "
        "cache), shared packet-prefix serialization "
        "(`frame.publish_prefix`: one serialize per wire form, "
        "packet-id spliced per receiver).  Rates are the median of 3 "
        "runs (`python bench.py --fanout`, `make fanout-bench`).  The "
        "1k row is cache-resident (every receiver object stays in "
        "LLC); per-delivery cost across the 10k -> 100k span is the "
        "honest flatness figure for at-scale broadcasts.",
        "",
        "| subscribers | expansion deliveries/s | wire deliveries/s "
        "| per-delivery ns | expansion vs wire |",
        "|---|---|---|---|---|",
    ]
    for r in s["rows"]:
        lines.append(
            f"| {r['subscribers']:,} | {r['expansion_rps']:,.0f} "
            f"| {r['wire_rps']:,.0f} | {r['per_delivery_ns']:,.0f} "
            f"| {r['expansion_vs_wire']:.1f}x |"
        )
    lines += [
        "",
        f"Wire path at 50k subscribers: "
        f"{s['wire_rps_50k']:,.0f} deliveries/s = "
        f"{s['vs_pre_rework_50k']:.1f}x the pre-rework row "
        f"({FANOUT_BASELINE_50K:,.0f}/s).  Per-delivery flatness: "
        f"{s['flat_ratio_10k_100k']:.2f}x across 10k -> 100k "
        f"({s['flat_ratio_1k_100k']:.2f}x from the cache-resident 1k "
        "row).",
        "",
    ]
    return lines


def _update_fanout_table(s: dict) -> None:
    """Replace the fan-out section of BENCH_TABLE.md in place (same
    ownership contract as the restore/ds sections)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == FANOUT_HEADER:
            skipping = True
            continue
        # drop the pre-PR9 inline paragraph+table too (it had no ##
        # header of its own)
        if line.startswith("Dispatch fan-out (host-side, match excluded"):
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    out += _fanout_section_lines(s)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md delivery-plane fan-out section")


def wire_fanout_rate(n: int) -> float:
    """Fan-out through the FULL channel path (session QoS + packet
    build + wire serialization — the shared-serialization fast path),
    i.e. what a real socketed subscriber costs minus the kernel write."""
    from emqx_tpu.broker import packet as pkt
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.frame import serialize_cached
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.message import Message

    class _NullConn:
        """The serialize stage of Connection._send_actions (shares the
        real serialize_cached helper so the bench can't drift)."""

        __slots__ = ("channel",)

        def __init__(self, channel):
            self.channel = channel

        def send_actions(self, actions):
            for action in actions:
                if action[0] == "send":
                    serialize_cached(action[1], self.channel.proto_ver)

    b = Broker()
    for i in range(n):
        ch = Channel(b, peername="127.0.0.1:1")
        ch.out_cb = _NullConn(ch).send_actions
        ch.on_kick = lambda rc: None
        ch.handle_in(pkt.Connect(proto_name="MQTT", proto_ver=5,
                                 clientid=f"w{i}"))
        ch.handle_in(pkt.Subscribe(
            packet_id=1, topic_filters=[("wide/t", pkt.SubOpts(qos=0))]
        ))
    fid = b.engine.fid_of("wide/t")
    iters = max(2, 100_000 // n)
    b._dispatch(Message(topic="wide/t", payload=b"x" * 128), {fid})
    t0 = time.time()
    for _ in range(iters):
        b._dispatch(Message(topic="wide/t", payload=b"x" * 128), {fid})
    return iters * n / (time.time() - t0)


WIRE_HEADER = "## Process-sharded wire plane"

# RSS gate workload: resident filters seeded into the match plane
# AFTER the throughput reps (so the rps rows stay comparable) to show
# table bytes are O(1) across the pool in shm mode — override with
# BENCH_WIRE_RESIDENT
WIRE_RESIDENT = int(os.environ.get("BENCH_WIRE_RESIDENT", 1_000_000))


def _rss_kb(pid: int) -> int:
    """VmRSS of a live process in kB (0 when unreadable)."""
    try:
        with open(f"/proc/{pid}/status", "r", encoding="ascii") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return int(ln.split()[1])
    except OSError:
        pass
    return 0


async def _wire_run_one(workers: int, duration: float, reps: int,
                        n_subs: int, n_pubs: int, payload: int,
                        shm: bool = True,
                        resident: int = WIRE_RESIDENT,
                        drain: str = "auto") -> dict:
    """One pool size W through REAL sockets: boot a hub + W wire
    workers (W=0 = the in-process listener path), attach `n_subs`
    subscribers to one fan-out filter and `n_pubs` flat-out QoS0
    publishers, and count PUBLISH packets landing at the subscriber
    sockets.  Connections round-robin over the per-worker direct ports
    so the distribution is deterministic (reuseport's 4-tuple hash is
    opaque for same-host clients) and every cross-worker IPC forward
    leg is actually exercised."""
    import tempfile

    from emqx_tpu.broker.client import MqttClient
    from emqx_tpu.node import NodeRuntime

    d = tempfile.mkdtemp(prefix=f"wirebench{workers}")
    raw = {
        "node": {"name": "bench-hub", "data_dir": d,
                 "xla_cache_dir": os.path.join(
                     tempfile.gettempdir(), "etpu-bench-xla-cache")},
        "listeners": [{"type": "tcp", "port": 0}],
        "dashboard": {"listen_port": 0},
    }
    if workers:
        raw["wire"] = {"workers": workers, "stats_interval": 0.5}
        # shm=False = the per-process layout (every worker boots its
        # own device engine), the pre-shared-match baseline; `drain`
        # picks the hub wakeup discipline (poll = the legacy 2ms loop,
        # auto = doorbell-driven native/thread waiter).  The doorbell
        # arm arms the adaptive fusion window: a doorbell wakes on the
        # FIRST commit, so without wait-to-fuse it would trade the
        # poll loop's accidental batching for unfused passes
        raw["shm"] = {"enable": bool(shm), "drain": drain,
                      "fuse_window_us": 0 if drain == "poll" else 500}
    rt = NodeRuntime(raw)
    await rt.start()
    try:
        if workers:
            sup = rt.wire
            deadline = time.time() + 120
            while time.time() < deadline and not all(
                rt.cluster.status().get(h.name) == "up"
                for h in sup.workers.values()
            ):
                await asyncio.sleep(0.2)
            ports = [h.direct_port for h in sup.workers.values()]
        else:
            ports = [rt.listeners[0].port]

        subs = []
        counts = [0] * n_subs
        for i in range(n_subs):
            c = MqttClient(clientid=f"ws{i}")
            await c.connect(port=ports[i % len(ports)])
            await c.subscribe("wire/bench", qos=0)
            subs.append(c)
        pubs = []
        for i in range(n_pubs):
            c = MqttClient(clientid=f"wp{i}")
            await c.connect(port=ports[i % len(ports)])
            pubs.append(c)
        await asyncio.sleep(1.0 if workers else 0.2)  # route fan-out

        stop = asyncio.Event()
        body = b"x" * payload
        published = [0]

        async def drain_sub(k: int) -> None:
            while not stop.is_set():
                try:
                    await subs[k].recv(timeout=0.2)
                except asyncio.TimeoutError:
                    continue
                counts[k] += 1

        # CLOSED-LOOP pump: each publish owes n_subs deliveries; the
        # pump stays at most `credit` deliveries ahead of what the
        # subscriber sockets actually received.  An open-loop flood
        # measures bufferbloat (and on an oversubscribed host, collapse
        # — kernel buffers absorb minutes of backlog); the credit
        # window self-clocks the offered load to whatever the system
        # under test can deliver, on any core count.
        credit = 32 * n_subs

        async def pump(c) -> None:
            while not stop.is_set():
                if published[0] * n_subs - sum(counts) > credit:
                    await asyncio.sleep(0.002)
                    continue
                await c.publish("wire/bench", body, qos=0)
                published[0] += 1
                # drain() on an under-watermark buffer completes
                # synchronously (no suspension): yield explicitly so
                # the subscriber reads sharing this loop make progress
                await asyncio.sleep(0)

        rep_rates = []
        for _rep in range(reps):
            for k in range(n_subs):
                counts[k] = 0
            published[0] = 0
            stop.clear()
            tasks = [asyncio.ensure_future(drain_sub(k))
                     for k in range(n_subs)]
            tasks += [asyncio.ensure_future(pump(c)) for c in pubs]
            t0 = time.time()
            await asyncio.sleep(duration)
            stop.set()
            await asyncio.gather(*tasks, return_exceptions=True)
            wall = time.time() - t0
            rep_rates.append(sum(counts) / wall)
        rep_rates.sort()
        med = rep_rates[len(rep_rates) // 2]
        spread = ((rep_rates[-1] - rep_rates[0]) / med * 100.0) \
            if med else 0.0
        per_worker = {}
        if workers:
            await asyncio.sleep(1.0)  # one more stats scrape
            g = rt.broker.metrics.gauges
            per_worker = {
                h.idx: {
                    "conns": g.get(f"wire.worker.{h.idx}.connections",
                                   0.0),
                    "sent": (h.last_stats or {}).get(
                        "messages_sent", 0),
                }
                for h in rt.wire.workers.values()
            }
        # cross-worker fusion: in shm mode every worker tick lands as
        # a foreign group on the HUB engine, whose flight recorder
        # carries the coalesced group size (`grp` column, prep_group)
        grp_max, grp_gt1_pct = 0, 0.0
        if workers and shm and rt.broker.engine.flight is not None:
            grps = [
                r["prep_group"]
                for r in rt.broker.engine.flight.recent(4096)
            ]
            if grps:
                grp_max = max(grps)
                grp_gt1_pct = (
                    sum(1 for x in grps if x > 1) / len(grps) * 100.0
                )
        # hub drain-engine telemetry (doorbell vs poll A/B columns)
        hub_drain = {}
        if workers and shm and rt.wire is not None \
                and rt.wire.service is not None:
            st = rt.wire.service.stats()
            hub_drain = {
                "drain_mode": st["drain_mode"] or "poll",
                "fused_share_pct": round(st["fused_share"] * 100.0, 1),
                "doorbell_wakeups": st["doorbell_wakeups"],
                "idle_passes": st["idle_passes"],
                "drain_passes": st["drain_passes"],
            }
        # memory gate: seed the resident filter set AFTER the reps (so
        # rps rows stay comparable) and read per-process RSS — in shm
        # mode the table lives once on the hub and worker RSS must stay
        # flat from W=1 to W=2
        if workers and shm and resident:
            rt.broker.engine.add_filters(
                [f"bench/resident/{i}/+" for i in range(resident)]
            )
        worker_rss = {}
        if workers:
            for h in rt.wire.workers.values():
                if h.proc is not None and h.proc.poll() is None:
                    worker_rss[str(h.idx)] = _rss_kb(h.proc.pid) // 1024
        hub_rss_mb = _rss_kb(os.getpid()) // 1024
        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        total = sum(s["sent"] for s in per_worker.values()) or 1
        return {
            "workers": workers,
            "shm": bool(shm) if workers else None,
            "drain": (drain if (workers and shm) else None),
            "hub_drain": hub_drain,
            "rps": med,
            "reps": [round(r, 1) for r in rep_rates],
            "rep_spread_pct": spread,
            "n_subs": n_subs,
            "n_pubs": n_pubs,
            "resident": resident if (workers and shm) else 0,
            "grp_max": grp_max,
            "grp_gt1_pct": round(grp_gt1_pct, 1),
            "hub_rss_mb": hub_rss_mb,
            "worker_rss_mb": worker_rss,
            # per-worker occupancy: share of wire deliveries each
            # worker served (from its own messages.sent counter)
            "occupancy": {
                str(i): round(s["sent"] / total, 3)
                for i, s in per_worker.items()
            },
            "conns": {
                str(i): s["conns"] for i, s in per_worker.items()
            },
        }
    finally:
        await rt.stop()


def run_wire(workers_list=(0, 1, 2), duration: float = 4.0,
             reps: int = 3, n_subs: int = 30, n_pubs: int = 2,
             payload: int = 128) -> dict:
    """Process-sharded wire plane sweep: aggregate wire deliveries/s
    over real TCP sockets at each pool size, vs the in-process (W=0)
    listener path.  One fresh interpreter per pool size (same reason
    as the --all config runs: a second engine generation in one
    process degrades per-call match latency ~1000x).  On a
    1-hardware-thread container the workers time-share one core, so
    the W>=2 rows measure IPC overhead, not scaling — the sweep
    exists so multi-core hosts get an honest ratio from the same
    command (`make wire-bench`)."""
    import subprocess
    import tempfile

    # every W>0 size runs BOTH engine layouts: shm=off is the
    # per-process baseline (each worker owns a device engine), shm=on
    # the shared-match plane — the w1 pair is the no-regression gate.
    # The shm layout additionally runs BOTH hub drain disciplines
    # (poll = legacy 2ms loop, auto = doorbell waiter) for the A/B.
    cases = []
    for w in workers_list:
        if w == 0:
            cases.append((0, True, "auto"))
        else:
            cases.extend([(w, False, "auto"),
                          (w, True, "poll"), (w, True, "auto")])
    rows = []
    for w, shm, drain in cases:
        if w == 0:
            tag = ""
        elif not shm:
            tag = " per-proc"
        else:
            tag = " shm/poll" if drain == "poll" else " shm/doorbell"
        log(f"wire bench: workers={w}{tag}")
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            stats_path = tf.name
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--wire-one",
             str(w), "--wire-shm", str(int(shm)),
             "--wire-drain", drain,
             "--emit-stats", stats_path],
            stdout=subprocess.PIPE, timeout=1800,
        )
        if r.returncode != 0:
            log(f"wire bench w{w}{tag} failed (rc={r.returncode}); "
                "row omitted")
            os.unlink(stats_path)
            continue
        with open(stats_path, "r", encoding="utf-8") as f:
            rows.append(json.load(f))
        os.unlink(stats_path)
        log(f"  -> {rows[-1]['rps']:,.0f} deliveries/s "
            f"(reps {rows[-1]['reps']}, "
            f"spread {rows[-1]['rep_spread_pct']:.0f}%, "
            f"grp_max {rows[-1].get('grp_max', 0)})")
    base = rows[0]["rps"] if rows and rows[0]["workers"] == 0 else None
    for r in rows:
        r["vs_inproc"] = (r["rps"] / base) if base else None
    host_threads = os.cpu_count() or 1
    return {
        "rows": rows,
        "host_threads": host_threads,
        "n_subs": n_subs,
        "n_pubs": n_pubs,
        "payload": payload,
    }


def _wire_section_lines(s: dict) -> list:
    lines = [
        "",
        f"{WIRE_HEADER} (aggregate wire deliveries/s, real sockets)",
        "",
        f"Hub + W wire-worker PROCESSES (SO_REUSEPORT listener pool, "
        f"unix-socket PeerLinks, see README): {s['n_subs']} socketed "
        f"subscribers on one fan-out filter, {s['n_pubs']} flat-out "
        "QoS0 publishers, connections round-robined over the workers "
        "so every cross-worker IPC forward leg is exercised.  W=0 is "
        "the in-process listener path (the pre-wire-plane broker).  "
        "Engine column: per-proc = every worker boots its own device "
        "engine (the pre-shm layout); shm = the shared-memory match "
        "plane (workers submit pre-packed ticks to the hub's single "
        "engine over SPSC rings), run twice for the drain A/B — "
        "shm/poll is the legacy fixed-interval hub drain loop, "
        "shm/doorbell the eventfd-driven drain engine (`shm.drain`, "
        "worker commits ring the parked hub; adaptive fusion window + "
        "per-lane credit).  grp>1 = share of hub dispatches "
        "that fused ticks from more than one worker (flight-recorder "
        "prep_group); RSS is measured per process AFTER seeding the "
        "resident filter set into the match plane — in shm mode the "
        "table lives ONCE on the hub, so worker RSS stays flat as W "
        "grows.  "
        f"Host: {s['host_threads']} hardware thread(s) — on a 1-thread "
        "host all workers time-share one core, so W>=2 rows measure "
        "the IPC tax and the >=1.8x-at-2-workers scaling gate needs a "
        "multi-core host; occupancy = each worker's share of wire "
        "deliveries (its own messages.sent), the balance check.",
        "",
        "| workers | engine | deliveries/s | vs in-process | reps | "
        "rep spread | grp>1 | worker RSS (MB) | hub RSS (MB) | "
        "occupancy |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in s["rows"]:
        occ = " / ".join(
            f"w{i}:{v:.0%}" for i, v in sorted(r["occupancy"].items())
        ) or "—"
        vs = f"{r['vs_inproc']:.2f}x" if r.get("vs_inproc") else "—"
        if r["workers"] == 0:
            eng = "in-proc"
        elif not r.get("shm"):
            eng = "per-proc"
        else:
            # shm rows carry the hub drain discipline of the A/B
            mode = (r.get("hub_drain") or {}).get(
                "drain_mode", r.get("drain") or "auto")
            eng = "shm/poll" if mode == "poll" else "shm/doorbell"
        grp = (
            f"{r['grp_gt1_pct']:.0f}% (max {r['grp_max']})"
            if r.get("grp_max") else "—"
        )
        wrss = " / ".join(
            f"w{i}:{v}" for i, v in
            sorted((r.get("worker_rss_mb") or {}).items())
        ) or "—"
        lines.append(
            f"| {r['workers']} | {eng} | {r['rps']:,.0f} | {vs} "
            f"| {', '.join(f'{x:,.0f}' for x in r['reps'])} "
            f"| ±{r['rep_spread_pct']:.0f}% | {grp} | {wrss} "
            f"| {r.get('hub_rss_mb', 0)} | {occ} |"
        )
    if any(r.get("resident") for r in s["rows"]):
        res = max(r.get("resident") or 0 for r in s["rows"])
        lines.append("")
        lines.append(
            f"RSS measured with {res:,} resident filters seeded into "
            "the match plane after the throughput reps (hub-side in "
            "shm mode: table bytes are O(1) across the pool)."
        )
    lines.append("")
    return lines


def _update_wire_table(s: dict) -> None:
    """Replace the wire-plane section of BENCH_TABLE.md in place."""
    path = "BENCH_TABLE.md"
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        text = "# BASELINE.json workload table\n"
    lines = text.split("\n")
    out, skip = [], False
    for ln in lines:
        if ln.startswith(WIRE_HEADER):
            skip = True
            continue
        if skip and ln.startswith("## "):
            skip = False
        if not skip:
            out.append(ln)
    while out and out[-1] == "":
        out.pop()
    out.extend(_wire_section_lines(s))
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    log("updated BENCH_TABLE.md wire-plane section")


SHM_HEADER = "## Shared-memory match plane"


def run_shm(n_filters: int = 2000, ticks: int = 600,
            batch: int = 16, fuse_ticks: int = 300,
            drain: str = "auto",
            fuse_window_us: int = 0) -> dict:
    """In-process microbench of the shm match plane (emqx_tpu/shm/):
    one hub MatchService + client lanes over REAL shared-memory rings,
    threads standing in for worker processes — the ring protocol is
    byte-identical, process isolation is exercised by `--wire` and the
    chaos tests.  Measures the submit->result round-trip at one lane,
    cross-lane fusion (two lanes submitting concurrently, group sizes
    from the service counters), churn-ack throughput through the same
    rings, plus the drain-engine figures of the poll-vs-doorbell A/B:
    idle hub wakeups/s (the tax the doorbells delete) and the
    drain-cycle gap under flat-out load."""
    import threading

    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.observe.flight import LatencyHistogram
    from emqx_tpu.ops.hashing import HashSpace
    from emqx_tpu.shm.client import ShmMatchEngine
    from emqx_tpu.shm.registry import ShmRegistry
    from emqx_tpu.shm.service import MatchService

    space = HashSpace()
    eng = TopicMatchEngine(space=space)
    reg = ShmRegistry(f"shm-bench-{os.getpid()}-{drain}")
    svc = MatchService(eng, reg, slots=64, slot_bytes=65536,
                       poll_interval=0.0005, drain=drain,
                       fuse_window_us=fuse_window_us)
    regions = [svc.create_lane(i) for i in range(2)]
    db_fds = [svc.doorbell_fd(i) if drain != "poll" else None
              for i in range(2)]
    loop = asyncio.new_event_loop()

    def run_loop():
        asyncio.set_event_loop(loop)
        svc.start()
        loop.run_forever()

    th = threading.Thread(target=run_loop, daemon=True)
    th.start()
    clients = [
        ShmMatchEngine(space=space, region=r, slots=64,
                       slot_bytes=65536, timeout=30.0,
                       doorbell_fd=db_fds[i])
        for i, r in enumerate(regions)
    ]
    try:
        # churn-ack throughput: the bulk add rides the churn ring in
        # 128-filter records, applied once by the hub; "done" = every
        # local fid mapped to its hub fid (full ack round trip)
        t0 = time.time()
        for k, cli in enumerate(clients):
            cli.add_filters(
                [f"lane{k}/f{i}/+" for i in range(n_filters)]
            )
        deadline = t0 + 120.0
        while any(c.stats()["unacked"] for c in clients):
            for c in clients:
                c.poll()
            time.sleep(0.001)
            if time.time() > deadline:
                raise RuntimeError(
                    "churn acks did not converge: "
                    + str([c.stats() for c in clients])
                )
        churn_rps = (2 * n_filters) / (time.time() - t0)

        # idle wakeup rate: no traffic for 1s — under poll the drain
        # loop turns at 1/poll_interval regardless; with doorbells it
        # parks and only the housekeeping bound (~1/s) turns it
        idle0 = svc.drain_passes
        time.sleep(1.0)
        idle_window = 1.0
        idle_wakeups_per_s = (svc.drain_passes - idle0) / idle_window

        topics = [f"lane0/f{i}/x" for i in range(batch)]
        clients[0].match(topics)  # warmup: first tick pays the compile
        lats = []
        for _ in range(ticks):
            t1 = time.perf_counter()
            out = clients[0].match(topics)
            lats.append(time.perf_counter() - t1)
            assert all(out), "resident filters must match"
        lats.sort()
        p50_us = lats[len(lats) // 2] * 1e6
        p99_us = lats[int(len(lats) * 0.99)] * 1e6

        # cross-lane fusion: both lanes submit flat out from their own
        # threads; the drain loop fuses same-geometry ticks into one
        # device call (groups < ticks)
        clients[1].match([f"lane1/f{i}/x" for i in range(batch)])
        ticks0, groups0 = svc.match_ticks, svc.match_groups
        gap0 = svc.hist_drain.counts.copy()
        t2 = time.time()

        def pump(k):
            tl = [f"lane{k}/f{i}/x" for i in range(batch)]
            for _ in range(fuse_ticks):
                clients[k].match(tl)

        threads = [threading.Thread(target=pump, args=(k,))
                   for k in range(2)]
        for x in threads:
            x.start()
        for x in threads:
            x.join()
        fuse_wall = time.time() - t2
        dticks = svc.match_ticks - ticks0
        dgroups = svc.match_groups - groups0
        degraded = sum(c.stats()["degraded"] for c in clients)
        local = sum(c.stats()["local"] for c in clients)
        # drain-cycle gap during the flat-out phase only (delta
        # histogram: the idle window's second-long parks stay out)
        gap = LatencyHistogram()
        gap.counts = svc.hist_drain.counts - gap0
        gap.count = int(gap.counts.sum())
        st = svc.stats()
        return {
            "drain": drain,
            "drain_mode": st["drain_mode"] or "poll",
            "fuse_window_us": fuse_window_us,
            "fuse_waits": st["fuse_waits"],
            "idle_wakeups_per_s": round(idle_wakeups_per_s, 1),
            "doorbell_wakeups": st["doorbell_wakeups"],
            "drain_gap_p50_us": round(gap.quantile(0.5) * 1e6, 1),
            "drain_gap_p99_us": round(gap.quantile(0.99) * 1e6, 1),
            "n_filters": 2 * n_filters,
            "churn_ack_rps": round(churn_rps, 1),
            "tick_p50_us": round(p50_us, 1),
            "tick_p99_us": round(p99_us, 1),
            "batch": batch,
            "fuse_ticks": dticks,
            "fuse_groups": dgroups,
            "fused_pct": round(
                (1.0 - dgroups / dticks) * 100.0, 1) if dticks else 0.0,
            "fuse_ticks_per_s": round(dticks / fuse_wall, 1)
            if fuse_wall else 0.0,
            "degraded": degraded,
            "local": local,
            "host_threads": os.cpu_count() or 1,
        }
    finally:
        fut = asyncio.run_coroutine_threadsafe(svc.stop(), loop)
        try:
            fut.result(10)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        th.join(10)
        for c in clients:
            c.close()
        svc.close()
        loop.close()


def run_shm_ab() -> dict:
    """The `--shm` drain A/B: the poll and doorbell arms each run in a
    FRESH interpreter (`--shm-one`, same hygiene as the --wire sweep —
    a second engine generation in one process degrades per-call match
    latency ~1000x), poll first so the legacy row is the baseline."""
    import subprocess
    import tempfile

    arms = []
    # the doorbell arm runs with the adaptive fusion window armed
    # (shm.fuse_window_us): a doorbell wakes the hub on the FIRST
    # commit, so without the wait-to-fuse window it would trade the
    # poll loop's accidental batching for unfused single-tick passes
    for arm, fuse_us in (("poll", 0), ("auto", 500)):
        log(f"shm bench: drain={arm} fuse_window_us={fuse_us}")
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            stats_path = tf.name
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--shm-one",
             arm, "--shm-fuse-us", str(fuse_us),
             "--emit-stats", stats_path],
            stdout=subprocess.PIPE, timeout=1800,
        )
        if r.returncode != 0:
            log(f"shm bench arm {arm} failed (rc={r.returncode}); "
                "row omitted")
            os.unlink(stats_path)
            continue
        with open(stats_path, "r", encoding="utf-8") as f:
            arms.append(json.load(f))
        os.unlink(stats_path)
        a = arms[-1]
        log(f"  -> {a['drain_mode']}: {a['fuse_ticks_per_s']:,.0f} "
            f"ticks/s, fused {a['fused_pct']:.0f}%, idle "
            f"{a['idle_wakeups_per_s']:,.0f} wakeups/s")
    return {"arms": arms, "host_threads": os.cpu_count() or 1}


def _shm_section_lines(s: dict) -> list:
    lines = [
        "",
        f"{SHM_HEADER} (in-process ring microbench)",
        "",
        "One hub MatchService + 2 client lanes over real "
        "shared-memory SPSC rings (threads stand in for worker "
        "processes; the ring protocol is byte-identical).  Round trip "
        "= TopicPrep pack into the slab -> hub drain -> one device "
        "call -> result scatter -> worker-side exact verify.  Fused % "
        "= hub dispatches that coalesced ticks from both lanes into "
        "one device call when both submit flat out.  Drain A/B: poll "
        "= the legacy fixed-interval drain loop (shm.poll_interval), "
        "native/thread = the doorbell-driven drain engine (worker "
        "commits ring a parked hub over per-lane eventfds; "
        "`shm.drain`).  idle wakeups/s = drain passes during a 1 s "
        "quiet window (the poll tax the doorbells delete); drain gap "
        "= pass-to-pass latency under flat-out 2-lane load.  Host: "
        f"{s['host_threads']} hardware thread(s).",
        "",
        "| drain | resident filters | churn acks/s | tick p50 "
        "| tick p99 | 2-lane ticks/s | fused | drain gap p50/p99 "
        "| idle wakeups/s | degraded |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for a in s["arms"]:
        mode = a["drain_mode"]
        if a.get("fuse_window_us"):
            mode += f" +{a['fuse_window_us']}µs fuse"
        lines.append(
            f"| {mode} | {a['n_filters']:,} "
            f"| {a['churn_ack_rps']:,.0f} "
            f"| {a['tick_p50_us']:,.0f} µs | {a['tick_p99_us']:,.0f} µs "
            f"| {a['fuse_ticks_per_s']:,.0f} | {a['fused_pct']:.0f}% "
            f"| {a['drain_gap_p50_us']:,.0f}/{a['drain_gap_p99_us']:,.0f} µs "
            f"| {a['idle_wakeups_per_s']:,.0f} "
            f"| {a['degraded']} |"
        )
    lines.append("")
    return lines


def _update_shm_table(s: dict) -> None:
    """Replace the shm-plane section of BENCH_TABLE.md in place."""
    path = "BENCH_TABLE.md"
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        text = "# BASELINE.json workload table\n"
    lines = text.split("\n")
    out, skip = [], False
    for ln in lines:
        if ln.startswith(SHM_HEADER):
            skip = True
            continue
        if skip and ln.startswith("## "):
            skip = False
        if not skip:
            out.append(ln)
    while out and out[-1] == "":
        out.pop()
    out.extend(_shm_section_lines(s))
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    log("updated BENCH_TABLE.md shm-plane section")


SPANS_HEADER = "## Latency attribution"
SPAN_OVERHEAD_GATE_PCT = 2.0  # armed@1/64 vs disarmed on the wire path


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _span_pipeline_attribution(n_subs=512, ticks=200, batch=8):
    """Drive the FULL three-phase publish pipeline (hooks -> submit ->
    collect -> enqueue -> wire) plus the durable-log ds leg with spans
    at sample=1, and return the plane export.  Subscribers are real
    channels behind the serialize stage (the wire_fanout_rate harness),
    so the wire stage closes at an honest transport hand-off."""
    import shutil
    import tempfile

    from emqx_tpu.broker import packet as pkt
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.frame import serialize_cached
    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.packet import SubOpts
    from emqx_tpu.broker.session import Session
    from emqx_tpu.config.config import Config
    from emqx_tpu.ds.manager import DsManager
    from emqx_tpu.observe import spans as spansmod

    class _NullConn:
        __slots__ = ("channel",)

        def __init__(self, channel):
            self.channel = channel

        def send_actions(self, actions):
            for action in actions:
                if action[0] == "send":
                    serialize_cached(action[1], self.channel.proto_ver)

    spansmod.configure(sample=1, keep=32)
    b = Broker()
    for i in range(n_subs):
        ch = Channel(b, peername="127.0.0.1:1")
        ch.out_cb = _NullConn(ch).send_actions
        ch.on_kick = lambda rc: None
        ch.handle_in(pkt.Connect(proto_name="MQTT", proto_ver=5,
                                 clientid=f"s{i}"))
        ch.handle_in(pkt.Subscribe(
            packet_id=1, topic_filters=[("wide/t", pkt.SubOpts(qos=0))]
        ))
    # parked persistent session with a replay cursor: QoS1 publishes
    # matching it ride dispatch -> deliver_offline -> ds append (the
    # "ds" leg), through the real offline path
    ddir = tempfile.mkdtemp(prefix="span_ds_")
    try:
        ds = DsManager(b, ddir, Config({}))
        b.ds = ds
        parked = Session(clientid="parked")
        parked.subscriptions["park/t"] = SubOpts(qos=1)
        parked.ds_cursor = ds.end_cursor()
        b.cm.pending["parked"] = (parked, time.time() + 3600)
        b.subscribe("parked", "park/t", SubOpts(qos=1))
        t0 = time.time()
        for _ in range(ticks):
            msgs = [Message(topic="wide/t", payload=b"x" * 64)
                    for _ in range(batch - 1)]
            msgs.append(Message(topic="park/t", payload=b"x" * 64,
                                qos=1))
            b.publish_many(msgs)
        wall_s = time.time() - t0
        ds.close()
    finally:
        shutil.rmtree(ddir, ignore_errors=True)
    export = spansmod.plane().export()
    export["pipeline_msgs"] = ticks * batch
    export["pipeline_wall_s"] = wall_s
    spansmod.disable()
    return export


async def _span_forward_leg(n_msgs=100):
    """2-node loopback cluster: sampled publishes on the origin, a
    subscriber on the peer — the REMOTE broker closes the forward leg
    (span context rides the FORWARD frame header)."""
    import asyncio

    from emqx_tpu.broker.message import Message
    from emqx_tpu.broker.packet import SubOpts
    from emqx_tpu.broker.session import Session
    from emqx_tpu.cluster.node import ClusterBroker, ClusterNode
    from emqx_tpu.observe import spans as spansmod

    spansmod.configure(sample=1, keep=32)
    nodes = []
    for i in range(2):
        node = ClusterNode(f"span{i}", ClusterBroker(),
                           heartbeat_ivl=0.5)
        await node.start()
        nodes.append(node)
    n0, n1 = nodes
    n0.join(n1.name, ("127.0.0.1", n1.transport.port))
    n1.join(n0.name, ("127.0.0.1", n0.transport.port))

    class _Sink:
        def __init__(self, clientid, session):
            self.clientid = clientid
            self.session = session
            self.got = []

        def deliver(self, items):
            self.got.extend(items)

        def kick(self, rc=0):
            pass

    s = Session(clientid="fw")
    s.subscriptions["fw/t"] = SubOpts(qos=0)
    sink = _Sink("fw", s)
    n1.broker.cm.register_channel(sink)
    n1.broker.subscribe("fw", "fw/t", SubOpts(qos=0))

    async def _wait(pred, timeout=15.0):
        t = 0.0
        while not pred():
            await asyncio.sleep(0.02)
            t += 0.02
            if t > timeout:
                raise RuntimeError("span forward leg: condition timed out")

    await _wait(lambda: "fw/t" in n0.remote.filters_of(n1.name))
    for _ in range(n_msgs):
        n0.broker.publish(Message(topic="fw/t", payload=b"x"))
        # yield between publishes so forward frames drain as they are
        # written — the leg then measures transport+dispatch latency,
        # not the tail of a 100-deep write-buffer burst
        await asyncio.sleep(0)
    await _wait(lambda: len(sink.got) >= n_msgs)
    await _wait(
        lambda: spansmod.plane().hists["forward"].count >= n_msgs
    )
    for node in nodes:
        await node.stop()
    export = spansmod.plane().export()
    spansmod.disable()
    return export


def _span_wire_ab(n=10_000, reps=7, disarmed_only=False):
    """Armed-at-1/64 vs disarmed A/B on the fan-out wire path, built
    to survive container noise: ONE shared broker/population (no
    per-leg heap drift), a gc.collect before each timed loop, and
    alternating measurement order with per-mode medians — the same
    interleaved discipline the mesh depth controller uses."""
    import gc

    from emqx_tpu.broker import packet as pkt
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.frame import serialize_cached
    from emqx_tpu.broker.message import Message
    from emqx_tpu.observe import spans as spansmod

    class _NullConn:
        __slots__ = ("channel",)

        def __init__(self, channel):
            self.channel = channel

        def send_actions(self, actions):
            for action in actions:
                if action[0] == "send":
                    serialize_cached(action[1], self.channel.proto_ver)

    b = Broker()
    for i in range(n):
        ch = Channel(b, peername="127.0.0.1:1")
        ch.out_cb = _NullConn(ch).send_actions
        ch.on_kick = lambda rc: None
        ch.handle_in(pkt.Connect(proto_name="MQTT", proto_ver=5,
                                 clientid=f"w{i}"))
        ch.handle_in(pkt.Subscribe(
            packet_id=1, topic_filters=[("wide/t", pkt.SubOpts(qos=0))]
        ))
    fid = b.engine.fid_of("wide/t")
    iters = max(4, 400_000 // n)

    def one_rate() -> float:
        # pre-build the batch and fence GC out of the timed loop: a
        # gen-2 sweep landing in one leg but not its pair is the
        # dominant noise source on this container
        msgs = [Message(topic="wide/t", payload=b"x" * 128)
                for _ in range(iters)]
        b._dispatch(Message(topic="wide/t", payload=b"x" * 128),
                    {fid})  # warm (fast-cb cache, prefix cache)
        gc.collect()
        gc.disable()
        try:
            t0 = time.time()
            for msg in msgs:
                b._dispatch(msg, {fid})
            dt = time.time() - t0
        finally:
            gc.enable()
        return iters * n / dt

    one_rate()  # first-touch warmup outside any timed pair
    dis_rates, armed_rates, pair_deltas = [], [], []
    for rep in range(reps):
        order = ((False,) if disarmed_only
                 else (False, True) if rep % 2 == 0 else (True, False))
        pair = {}
        for armed in order:
            if armed:
                spansmod.configure(sample=64, keep=64)
                pair[True] = one_rate()
                armed_rates.append(pair[True])
            else:
                spansmod.disable()
                pair[False] = one_rate()
                dis_rates.append(pair[False])
        if len(pair) == 2:
            # paired delta: the two legs run back to back, so slow
            # drift (heap growth, container scheduling) cancels —
            # medians of independent legs don't converge under the
            # +-10% per-loop noise this container shows
            pair_deltas.append(
                (pair[False] - pair[True]) / pair[False] * 100.0
            )
    spansmod.disable()
    return dis_rates, armed_rates, pair_deltas


def _span_boundary_ns(loops: int = 5, iters: int = 200_000) -> float:
    """Cost of ONE disarmed span boundary (the `spans.armed`
    module-attribute bool test — the only thing the plane adds to an
    unsampled path), min over tight loops so scheduler preemption can
    only inflate, not deflate.  The measured value includes the timing
    loop's own per-iteration cost, so it is an UPPER bound.  The
    disarmed-overhead gate is structural: the wire path executes one
    such check per BROADCAST (scatter lane) or per connection flush
    batch — never per delivery — so the per-delivery overhead is this
    number divided by the batch fan-out."""
    from emqx_tpu.observe import spans as spansmod

    spansmod.disable()
    best = float("inf")
    for _ in range(loops):
        t0 = time.perf_counter()
        for _ in range(iters):
            if spansmod.armed:
                raise AssertionError  # disarmed by construction
        dt = (time.perf_counter() - t0) / iters * 1e9
        if dt < best:
            best = dt
    return best


def run_spans(reps: int = 7):
    """`--spans`: per-plane latency attribution + overhead A/B.

    Three legs: (1) overhead — the `--fanout` wire path at 10k
    subscribers, one shared population with alternating armed-at-
    default-1/64 vs disarmed timed loops (`BENCH_NO_SPANS=1` skips the
    armed legs so an external driver can A/B whole processes the way
    `BENCH_NO_FLIGHT` does); (2) attribution — the full publish
    pipeline incl. the ds leg at sample=1; (3) the cross-node forward
    leg on a 2-node loopback cluster."""
    import asyncio

    from emqx_tpu.observe import spans as spansmod
    from emqx_tpu.observe.spans import KNOWN_STAGES

    no_spans = os.environ.get("BENCH_NO_SPANS") == "1"
    n = 10_000
    log(f"span overhead A/B: fanout wire path, {n:,} subscribers")
    dis_rates, armed_rates, pair_deltas = _span_wire_ab(
        n, reps=3 if no_spans else reps, disarmed_only=no_spans
    )
    stats = {"wire_rps_disarmed": _median(dis_rates),
             "wire_reps_disarmed": [round(r, 1) for r in dis_rates]}
    if armed_rates:
        stats["wire_rps_armed"] = _median(armed_rates)
        stats["wire_reps_armed"] = [round(r, 1) for r in armed_rates]
        stats["armed_pair_deltas_pct"] = [
            round(d, 2) for d in pair_deltas
        ]
        stats["armed_overhead_pct"] = _median(pair_deltas)
    # disarmed overhead, structurally: the wire path runs ONE boundary
    # check per broadcast (scatter lane) / per connection flush batch,
    # never per delivery — measure the check, divide by the fan-out
    per_delivery_ns = 1e9 / stats["wire_rps_disarmed"]
    boundary_ns = _span_boundary_ns()
    stats["boundary_check_ns"] = round(boundary_ns, 2)
    stats["per_delivery_ns"] = round(per_delivery_ns, 1)
    stats["overhead_pct"] = (
        boundary_ns / (n * per_delivery_ns) * 100.0
    )
    # worst case: a non-scatter receiver pays one check per
    # single-message flush batch (1 check per delivery)
    stats["overhead_worst_case_pct"] = (
        boundary_ns / per_delivery_ns * 100.0
    )
    if no_spans:
        return stats

    log("span attribution: full pipeline at sample=1")
    pipeline = _span_pipeline_attribution()
    log("span forward leg: 2-node loopback cluster")
    forward = asyncio.run(_span_forward_leg())
    # merge: pipeline stages + the cluster run's forward leg
    stages = dict(pipeline["stages"])
    stages["forward"] = forward["stages"]["forward"]
    stats["stages"] = stages
    stats["stage_p99_ms"] = {
        s: round(stages[s].get("p99", 0.0), 4)
        for s in KNOWN_STAGES if stages[s]["count"]
    }
    stats["stage_p50_ms"] = {
        s: round(stages[s].get("p50", 0.0), 4)
        for s in KNOWN_STAGES if stages[s]["count"]
    }
    stats["spans"] = pipeline
    stats["forward_legs_closed"] = forward["remote_closed"]
    return stats


def _spans_section_lines(s: dict) -> list:
    from emqx_tpu.observe.spans import KNOWN_STAGES

    lines = [
        "",
        SPANS_HEADER,
        "",
        "Message-lifecycle span plane (`observe/spans.py`, `python "
        "bench.py --spans`, `make span-bench`): head-sampled publishes "
        "stamp a monotonic timestamp at every plane boundary; "
        "per-stage deltas land in the flight recorder's mergeable log2 "
        "histograms (p50/p99/p999 are bucket-derived — upper bucket "
        "edges, never under-reporting the tail).  `hooks` -> `submit` "
        "-> `collect` -> `enqueue` -> `wire` is the three-phase "
        "publish pipeline at sample=1; `forward` is the cross-node leg "
        "closed by the REMOTE broker of a 2-node loopback cluster "
        "(span context rides the FORWARD frame header); `ds` is the "
        "parked-session durable-log append leg.  The submit p999 "
        "bucket catches the first tick's one-off XLA compile.  Render "
        "the slowest-K span waterfalls with `tools/span_dump.py`.",
        "",
        "| stage | samples | p50 ms | p99 ms | p999 ms |",
        "|---|---|---|---|---|",
    ]
    stages = s.get("stages") or {}
    for stage in KNOWN_STAGES:
        row = stages.get(stage) or {}
        if row.get("count"):
            lines.append(
                f"| {stage} | {row['count']:,} | {row['p50']:.3f} "
                f"| {row['p99']:.3f} | {row['p999']:.3f} |"
            )
        else:
            lines.append(f"| {stage} | 0 | - | - | - |")
    tail = (
        f"Disarmed overhead on the fan-out wire path (10k "
        f"subscribers, {s['wire_rps_disarmed']:,.0f} deliveries/s = "
        f"{s['per_delivery_ns']:,.0f} ns/delivery): the plane adds ONE "
        f"boundary check (the `spans.armed` attribute test, "
        f"{s['boundary_check_ns']:.0f} ns) per broadcast / per "
        f"connection flush batch — never per delivery — i.e. "
        f"{s['overhead_pct']:.5f}% at this fan-out and "
        f"{s['overhead_worst_case_pct']:.2f}% worst-case for "
        f"single-receiver flush batches (gate <= "
        f"{SPAN_OVERHEAD_GATE_PCT:.0f}%)."
    )
    if s.get("armed_overhead_pct") is not None:
        tail += (
            f"  Armed at the default 1/64 sampling, the paired "
            f"wall-clock A/B is indistinguishable from disarmed within "
            f"this container's noise: median paired delta "
            f"{s['armed_overhead_pct']:+.2f}% over "
            f"{len(s['armed_pair_deltas_pct'])} back-to-back pairs "
            f"(spread {min(s['armed_pair_deltas_pct']):+.1f}% .. "
            f"{max(s['armed_pair_deltas_pct']):+.1f}%)."
        )
    else:
        tail += "  (BENCH_NO_SPANS=1: armed legs skipped.)"
    lines += ["", tail, ""]
    return lines


def _update_spans_table(s: dict) -> None:
    """Replace the latency-attribution section of BENCH_TABLE.md in
    place (same ownership contract as the fanout/restore sections)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == SPANS_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    out += _spans_section_lines(s)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md latency-attribution section")


SHMSPAN_HEADER = "## Shm-lane attribution"
# reconciliation gate: sum of per-leg MEANS vs the measured end-to-end
# ring round-trip mean (same ticks feed both, so this is near-exact;
# bucket-derived p50/p99 sums can legitimately deviate up to one log2
# bucket per leg and are display-only)
SHMSPAN_RECON_GATE_PCT = 15.0
SHM_LEGS = ("ring_wait", "fuse_wait", "device", "scatter")


async def _spans_shm_one(armed: bool, duration: float = 6.0,
                         n_subs: int = 8, n_pubs: int = 2,
                         payload: int = 128,
                         drain: str = "auto") -> dict:
    """One arm of the shm-lane attribution A/B: boot the REAL hub +
    2-wire-worker shm topology (`worker_raw` derivations inherit the
    `observe` section, so both workers arm at sample=1 or disarm at
    0), drive a closed-loop publish pump over the per-worker direct
    ports, then scrape the supervisor's fleet export — the leg
    histograms arrive over the same wire_stats RPC production uses, so
    the bench measures the fleet aggregation path, not an in-process
    shortcut."""
    import tempfile

    from emqx_tpu.broker.client import MqttClient
    from emqx_tpu.node import NodeRuntime

    d = tempfile.mkdtemp(prefix="shmspan")
    raw = {
        "node": {"name": "bench-hub", "data_dir": d,
                 "xla_cache_dir": os.path.join(
                     tempfile.gettempdir(), "etpu-bench-xla-cache")},
        "listeners": [{"type": "tcp", "port": 0}],
        "dashboard": {"listen_port": 0},
        "wire": {"workers": 2, "stats_interval": 0.5},
        # poll arm keeps the legacy drain loop for the A/B; doorbell
        # arms ride the fusion window so the ring_wait/fuse_wait split
        # prices the wakeup discipline, not accidental batching
        "shm": {"enable": True, "drain": drain,
                "fuse_window_us": 0 if drain == "poll" else 500},
        "observe": {"span_sample": 1 if armed else 0},
    }
    rt = NodeRuntime(raw)
    await rt.start()
    try:
        sup = rt.wire
        deadline = time.time() + 120
        while time.time() < deadline and not all(
            rt.cluster.status().get(h.name) == "up"
            for h in sup.workers.values()
        ):
            await asyncio.sleep(0.2)
        ports = [h.direct_port for h in sup.workers.values()]

        subs = []
        counts = [0] * n_subs
        for i in range(n_subs):
            c = MqttClient(clientid=f"ss{i}")
            await c.connect(port=ports[i % len(ports)])
            await c.subscribe("shmspan/bench", qos=0)
            subs.append(c)
        pubs = []
        for i in range(n_pubs):
            c = MqttClient(clientid=f"sp{i}")
            await c.connect(port=ports[i % len(ports)])
            pubs.append(c)
        await asyncio.sleep(1.0)  # route fan-out settles

        stop = asyncio.Event()
        body = b"x" * payload
        published = [0]

        async def drain_sub(k: int) -> None:
            while not stop.is_set():
                try:
                    await subs[k].recv(timeout=0.2)
                except asyncio.TimeoutError:
                    continue
                counts[k] += 1

        # same closed-loop credit pump as _wire_run_one: offered load
        # self-clocks to what the topology delivers, so the armed and
        # disarmed arms see the same queueing regime
        credit = 32 * n_subs

        async def pump(c) -> None:
            while not stop.is_set():
                if published[0] * n_subs - sum(counts) > credit:
                    await asyncio.sleep(0.002)
                    continue
                await c.publish("shmspan/bench", body, qos=0)
                published[0] += 1
                await asyncio.sleep(0)

        tasks = [asyncio.ensure_future(drain_sub(k))
                 for k in range(n_subs)]
        tasks += [asyncio.ensure_future(pump(c)) for c in pubs]
        t0 = time.time()
        await asyncio.sleep(duration)
        stop.set()
        await asyncio.gather(*tasks, return_exceptions=True)
        wall = time.time() - t0
        rate = sum(counts) / wall
        # let two more stats scrapes land so the final cumulative
        # histograms (incl. the last ticks' legs) reach the supervisor
        await asyncio.sleep(1.2)
        fleet = sup.fleet_export()
        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        svc = getattr(sup, "service", None)
        return {
            "armed": bool(armed),
            "drain": drain,
            "drain_mode": (svc.drain_mode or svc.drain)
            if svc is not None else "",
            "rps": rate,
            "published": published[0],
            "fleet": fleet,
        }
    finally:
        await rt.stop()


def run_spans_shm(duration: float = 6.0) -> dict:
    """`--spans-shm` (`make fleet-bench`): shm-lane span attribution
    over the real hub + 2-worker topology.  Two subprocess arms (one
    fresh interpreter each, same hygiene as --wire): armed at
    sample=1 decomposes every ring round-trip into the
    ring_wait/fuse_wait/device/scatter legs; disarmed is the A/B
    reference for the <=2% overhead gate.  Reconciliation gate: the
    per-leg mean sum must land within SHMSPAN_RECON_GATE_PCT of the
    measured end-to-end round-trip mean (`hist_ring`)."""
    import subprocess
    import tempfile

    from emqx_tpu.observe.flight import LatencyHistogram

    runs = {}
    # three arms: armed doorbell (the decomposition + drain A/B side),
    # disarmed doorbell (overhead reference), armed poll (the legacy
    # drain loop priced by the same per-leg stamps)
    for tag, armed, drain in (("armed", 1, "auto"),
                              ("disarmed", 0, "auto"),
                              ("poll", 1, "poll")):
        log(f"shm-span bench: hub + 2 workers, spans "
            f"{'armed' if armed else 'disarmed'}, drain={drain}")
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            stats_path = tf.name
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--spans-shm-one", str(armed),
             "--spans-drain", drain,
             "--emit-stats", stats_path],
            stdout=subprocess.PIPE, timeout=1800,
        )
        if r.returncode != 0:
            os.unlink(stats_path)
            raise SystemExit(
                f"shm-span arm '{tag}' failed (rc={r.returncode})"
            )
        with open(stats_path, "r", encoding="utf-8") as f:
            runs[tag] = json.load(f)
        os.unlink(stats_path)
        log(f"  -> {runs[tag]['rps']:,.0f} deliveries/s")

    fleet = runs["armed"]["fleet"]
    fh = fleet.get("fleet_hists") or {}

    def _row(d) -> dict:
        if not d or not d.get("count"):
            return {"count": 0}
        h = LatencyHistogram.from_dict(d)
        p = h.percentiles_ms()
        return {
            "count": h.count,
            "p50_ms": round(p["p50"], 4),
            "p99_ms": round(p["p99"], 4),
            "mean_ms": round(h.sum / h.count * 1e3, 4),
        }

    legs = {
        leg: _row(fh.get(f"fleet_span_stage_{leg}_latency"))
        for leg in SHM_LEGS
    }
    ring = _row(fh.get("fleet_shm_ring_roundtrip"))
    leg_mean_sum = sum(
        r.get("mean_ms", 0.0) for r in legs.values()
    )
    leg_p50_sum = sum(r.get("p50_ms", 0.0) for r in legs.values())
    leg_p99_sum = sum(r.get("p99_ms", 0.0) for r in legs.values())
    recon_pct = (
        abs(leg_mean_sum - ring["mean_ms"]) / ring["mean_ms"] * 100.0
        if ring.get("mean_ms") else None
    )
    # per-worker round-trip rows: the balance check (both workers must
    # actually have exercised the shm hop, not just one)
    per_worker = {
        w.get("name", idx): _row(
            (w.get("hists") or {}).get("shm_ring_roundtrip")
        )
        for idx, w in (fleet.get("workers") or {}).items()
    }
    dis_rps = runs["disarmed"]["rps"]
    armed_rps = runs["armed"]["rps"]
    overhead_pct = (
        (dis_rps - armed_rps) / dis_rps * 100.0 if dis_rps else 0.0
    )
    hub = fleet.get("hub") or {}
    hub_stats = hub.get("stats") or {}
    # poll-arm decomposition: the same per-leg stamps under the legacy
    # drain loop — the ring_wait delta IS the drain-discipline price
    poll_fleet = runs["poll"]["fleet"]
    poll_fh = poll_fleet.get("fleet_hists") or {}
    poll_legs = {
        leg: _row(poll_fh.get(f"fleet_span_stage_{leg}_latency"))
        for leg in SHM_LEGS
    }
    poll_ring = _row(poll_fh.get("fleet_shm_ring_roundtrip"))
    poll_hub = (poll_fleet.get("hub") or {}).get("stats") or {}
    return {
        "legs": legs,
        "ring": ring,
        "leg_mean_sum_ms": round(leg_mean_sum, 4),
        "leg_p50_sum_ms": round(leg_p50_sum, 4),
        "leg_p99_sum_ms": round(leg_p99_sum, 4),
        "recon_pct": None if recon_pct is None else round(recon_pct, 2),
        "recon_gate_pct": SHMSPAN_RECON_GATE_PCT,
        "per_worker_ring": per_worker,
        "rps_armed": round(armed_rps, 1),
        "rps_disarmed": round(dis_rps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "overhead_gate_pct": SPAN_OVERHEAD_GATE_PCT,
        "drain_cycle_ms": hub_stats.get("drain_cycle_ms"),
        "group_sizes": hub_stats.get("group_sizes"),
        "drain_mode": runs["armed"].get("drain_mode", ""),
        "poll": {
            "legs": poll_legs,
            "ring": poll_ring,
            "rps": round(runs["poll"]["rps"], 1),
            "drain_cycle_ms": poll_hub.get("drain_cycle_ms"),
            "group_sizes": poll_hub.get("group_sizes"),
        },
        "fleet": fleet,
    }


def _spans_shm_section_lines(s: dict) -> list:
    lines = [
        "",
        SHMSPAN_HEADER,
        "",
        "Shm-hop decomposition of the worker's `collect` stage "
        "(`python bench.py --spans-shm`, `make fleet-bench`): a real "
        "hub + 2-wire-worker shm topology under the closed-loop "
        "publish pump, spans armed at sample=1.  Worker submits stamp "
        "a monotonic-ns timestamp into the slot header's spare bytes; "
        "the hub stamps drain/fuse/device-done and ships them back in "
        "the result record, and the worker decomposes each ring round "
        "trip into `ring_wait` (slot committed -> hub drain), "
        "`fuse_wait` (drain -> fused foreign_submit), `device` "
        "(submit -> collect done) and `scatter` (result committed -> "
        "worker decode).  Histograms cross the wire_stats RPC and are "
        "fleet-merged by the supervisor — this table IS the "
        "production aggregation path (`tools/fleet_dump.py` renders "
        "the same export).  Main table = the doorbell drain engine "
        "(`shm.drain: auto`, 500 µs fusion window); the drain A/B "
        "table below re-runs the armed leg under the legacy poll "
        "loop (`shm.drain: poll`), so the per-leg deltas price the "
        "wakeup discipline itself.",
        "",
        "| leg | samples | p50 ms | p99 ms | mean ms |",
        "|---|---|---|---|---|",
    ]
    for leg in SHM_LEGS:
        r = s["legs"].get(leg) or {}
        if r.get("count"):
            lines.append(
                f"| {leg} | {r['count']:,} | {r['p50_ms']:.3f} "
                f"| {r['p99_ms']:.3f} | {r['mean_ms']:.3f} |"
            )
        else:
            lines.append(f"| {leg} | 0 | - | - | - |")
    ring = s.get("ring") or {}
    if ring.get("count"):
        lines.append(
            f"| ring round-trip (measured) | {ring['count']:,} "
            f"| {ring['p50_ms']:.3f} | {ring['p99_ms']:.3f} "
            f"| {ring['mean_ms']:.3f} |"
        )
    per_w = ", ".join(
        f"{name}: {r['mean_ms']:.3f} ms mean over {r['count']:,}"
        for name, r in sorted(s.get("per_worker_ring", {}).items())
        if r.get("count")
    )
    if s.get("recon_pct") is None:
        lines += ["", "No armed leg data captured (run too short?).", ""]
        return lines
    tail = (
        f"Reconciliation: per-leg mean sum {s['leg_mean_sum_ms']:.3f} "
        f"ms vs measured round-trip mean "
        f"{ring.get('mean_ms', 0.0):.3f} ms = "
        f"{s['recon_pct']:.2f}% deviation (gate <= "
        f"{s['recon_gate_pct']:.0f}%; the same ticks feed both sides, "
        f"so this checks the stamp plumbing end to end).  Armed vs "
        f"disarmed delivery rate: {s['rps_armed']:,.0f} vs "
        f"{s['rps_disarmed']:,.0f} deliveries/s = "
        f"{s['overhead_pct']:+.2f}% span overhead at sample=1 (gate "
        f"<= {s['overhead_gate_pct']:.0f}%; container-noise dominated)."
    )
    if per_w:
        tail += f"  Per-worker round-trip: {per_w}."
    dc = s.get("drain_cycle_ms")
    if dc:
        tail += (
            f"  Hub drain cycle p50/p99: {dc.get('p50', 0.0):.3f}/"
            f"{dc.get('p99', 0.0):.3f} ms."
        )
    gs = s.get("group_sizes")
    if gs:
        dist = ", ".join(
            f"{k}: {v}" for k, v in sorted(
                gs.items(), key=lambda kv: int(kv[0])
            )
        )
        tail += f"  Fusion group sizes (size: dispatches): {dist}."
    lines += ["", tail, ""]
    poll = s.get("poll") or {}
    if poll.get("ring", {}).get("count"):
        mode = s.get("drain_mode") or "doorbell"
        lines += [
            f"Drain A/B (same armed leg, poll loop vs {mode} "
            "doorbells):",
            "",
            "| leg | poll p50 / mean ms | doorbell p50 / mean ms |",
            "|---|---|---|",
        ]
        for leg in SHM_LEGS:
            p = poll["legs"].get(leg) or {}
            d = s["legs"].get(leg) or {}
            if p.get("count") and d.get("count"):
                lines.append(
                    f"| {leg} | {p['p50_ms']:.3f} / {p['mean_ms']:.3f}"
                    f" | {d['p50_ms']:.3f} / {d['mean_ms']:.3f} |"
                )
        pring, dring = poll["ring"], s.get("ring") or {}
        if dring.get("count"):
            lines.append(
                "| ring round-trip "
                f"| {pring['p50_ms']:.3f} / {pring['mean_ms']:.3f} "
                f"| {dring['p50_ms']:.3f} / {dring['mean_ms']:.3f} |"
            )
        ab_tail = (
            f"Armed delivery rate poll vs doorbell: "
            f"{poll['rps']:,.0f} vs {s['rps_armed']:,.0f} "
            "deliveries/s."
        )
        pdc, ddc = poll.get("drain_cycle_ms"), s.get("drain_cycle_ms")
        if pdc and ddc:
            ab_tail += (
                f"  Hub drain cycle p50 poll vs doorbell: "
                f"{pdc.get('p50', 0.0):.3f} vs "
                f"{ddc.get('p50', 0.0):.3f} ms."
            )
        lines += ["", ab_tail, ""]
    return lines


def _update_spans_shm_table(s: dict) -> None:
    """Replace the shm-lane attribution section of BENCH_TABLE.md in
    place (same ownership contract as the other sections)."""
    path = "BENCH_TABLE.md"
    lines = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    out, skipping = [], False
    for line in lines:
        if line.strip() == SHMSPAN_HEADER:
            skipping = True
            continue
        if skipping and line.startswith("## "):
            skipping = False
        if not skipping:
            out.append(line)
    while out and not out[-1].strip():
        out.pop()
    out += _spans_shm_section_lines(s)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(out))
    log("updated BENCH_TABLE.md shm-lane attribution section")


CONFIGS = {
    1: ("exact_1k", "1k exact subs, single-level topics"),
    2: ("wild_100k", "100k subs, 6-level, 20% '+' wildcards"),
    3: ("mixed_1m", "1M subs, mixed '+'/'#', shared groups"),
    4: ("zipf_10m", "10M subs, Zipf-skewed publishes"),
    5: ("churn_10m", "10M subs, 5%/sec churn"),
}


def run_config(n: int, subs_cap: int | None):
    rng = random.Random(1234 + n)
    churn_frac, churn_pool = 0.0, None
    if n == 1:
        filters, topics_fn = pop_exact_1k(rng)
    elif n == 2:
        filters, topics_fn = pop_wild_100k(rng)
    elif n == 3:
        filters, topics_fn = pop_mixed(rng, subs_cap or 1_000_000)
    elif n == 4:
        filters, topics_fn = pop_zipf(rng, subs_cap or 10_000_000)
    elif n == 5:
        filters, topics_fn = pop_mixed(rng, subs_cap or 10_000_000)
        churn_frac = 0.05
        churn_pool = [f"churn/{i}/+" for i in range(50_000)]
    else:
        raise SystemExit(f"unknown config {n}")
    log(f"== config {n}: {CONFIGS[n][1]} ({len(filters):,} filters) ==")
    cpu_insert, cpu_rps, cpu_clean = cpu_baseline(filters, topics_fn,
                                                  churn_frac, churn_pool)
    stats = run_engine(filters, topics_fn, churn_frac, churn_pool)
    stats.update({"cpu_rps": cpu_rps, "cpu_insert_rps": cpu_insert,
                  "cpu_rps_clean": cpu_clean,
                  "n_filters": len(filters)})
    return stats


def headline_json(n: int, stats: dict) -> str:
    """value/vs_baseline = the PRODUCTION engine.match() rate (hybrid
    arbitration, verify on — what a broker.publish tick actually pays);
    the device-only e2e and raw kernel rates ride along."""
    best, passed = pick_north_star(stats.get("ns_rows"), stats["cpu_rps"],
                               stats.get("churn_target", 0.0))
    return json.dumps({
        "metric": f"route_lookups_per_sec_{CONFIGS[n][0]}",
        "value": round(stats["tpu_rps"]),
        "unit": "lookups/sec",
        "vs_baseline": round(stats["tpu_rps"] / stats["cpu_rps"], 2),
        "vs_cpu_clean": round(
            stats["tpu_rps"] / stats.get("cpu_rps_clean", stats["cpu_rps"]),
            2,
        ),
        "device": stats["device"],
        "north_star": None if best is None else {
            "tick": best["tick"],
            "rps": round(best["rps"]),
            "vs_baseline": round(best["rps"] / stats["cpu_rps"], 2),
            "p99_ms": round(best["p99_ms"], 3),
            "pass": passed,
            # all three sweep repetitions (the row above is the median
            # by rps): the gate can be audited against run-to-run noise
            "reps": best.get("reps"),
        },
        "p99_ms": round(stats["p99_ms"], 3),
        "p99_small_ms": round(stats.get("p99_small_ms", 0), 3),
        "hist_p50_ms": round(stats.get("hist_p50_ms", 0), 3),
        "hist_p99_ms": round(stats.get("hist_p99_ms", 0), 3),
        "dev_e2e_rps": round(stats["dev_e2e_rps"]),
        "dev_e2e_vs_baseline": round(
            stats["dev_e2e_rps"] / stats["cpu_rps"], 2
        ),
        "dev_e2e_p99_ms": round(stats["dev_p99_ms"], 3),
        "insert_rps": round(stats["insert_rps"]),
        "insert_vs_baseline": round(
            stats["insert_rps"] / stats["cpu_insert_rps"], 2
        ),
        "kernel_rps": round(stats["kernel_rps"]),
        "kernel_vs_baseline": round(stats["kernel_rps"] / stats["cpu_rps"], 2),
        "kernel_p99_ms": round(stats["kernel_p99_ms"], 3),
    })


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=None, choices=sorted(CONFIGS))
    ap.add_argument("--all", action="store_true",
                    help="run all 5 configs, write BENCH_TABLE.md (default "
                         "when --config is not given)")
    ap.add_argument("--subs", type=int, default=None,
                    help="cap filter count for configs 3-5")
    ap.add_argument("--emit-stats", default=None,
                    help="write this config's full stats JSON to a file")
    ap.add_argument("--sharded", nargs="?", const=2, default=None, type=int,
                    choices=(2, 3, 5),
                    help="run a BASELINE workload (2/3/5) on the mesh-"
                         "sharded engine over an 8-device virtual CPU mesh")
    ap.add_argument("--retained", action="store_true",
                    help="run the retained-index lookup bench only")
    ap.add_argument("--restore", action="store_true",
                    help="time snapshot+WAL warm restore vs cold table "
                         "rebuild at 100k filters; writes the "
                         "restore_ms/rebuild_ms row into BENCH_TABLE.md")
    ap.add_argument("--semantic", action="store_true",
                    help="semantic subscription plane bench: query-table "
                         "x publish-batch sweep of the device top-k vs "
                         "host dense scorer, kernel rate, arbiter "
                         "verdict, plus the e2e shm-hub leg; writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--ds", action="store_true",
                    help="offline-fanout replay bench: N parked sessions "
                         "x M offline messages, durable-log cursors vs "
                         "legacy per-session JSON snapshots; writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--takeover", action="store_true",
                    help="cross-node takeover of a 10k-message parked "
                         "queue: materialized session ship vs the "
                         "replicated-mirror cursor handoff (bytes on "
                         "the wire + latency); writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--churn", action="store_true",
                    help="churn-apply capacity worker sweep (parallel "
                         "churn plane vs python dicts at 1/2/4 workers, "
                         "one subprocess each); writes the BENCH_TABLE.md "
                         "section")
    ap.add_argument("--fanout", action="store_true",
                    help="delivery-plane fan-out sweep (one filter, "
                         "1k/10k/50k/100k subscribers): expansion vs "
                         "full wire path, per-delivery ns; writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--spans", action="store_true",
                    help="message-lifecycle span attribution: per-stage "
                         "p50/p99 across hooks/submit/collect/enqueue/"
                         "wire + forward + ds, plus the disarmed-"
                         "overhead A/B on the fan-out wire path "
                         "(BENCH_NO_SPANS=1 = disarmed leg only); "
                         "writes the BENCH_TABLE.md section")
    ap.add_argument("--spans-shm", action="store_true",
                    help="shm-lane span attribution over the real hub "
                         "+ 2-wire-worker shm topology: per-leg "
                         "ring_wait/fuse_wait/device/scatter p50/p99, "
                         "mean-sum reconciliation vs the measured ring "
                         "round-trip, armed-vs-disarmed overhead A/B "
                         "(`make fleet-bench`); writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--spans-shm-one", default=None, type=int,
                    choices=(0, 1),
                    help="single shm-span topology run, spans armed "
                         "(1) or disarmed (0) — the --spans-shm "
                         "sweep's inner subprocess")
    ap.add_argument("--spans-drain", default="auto",
                    choices=("auto", "poll"),
                    help="hub drain mode for --spans-shm-one (the "
                         "--spans-shm sweep's drain A/B arm)")
    ap.add_argument("--prep-only", action="store_true",
                    help="fused-native vs python-fallback prep "
                         "microbench at B=512/2048 over the sharded "
                         "workload's topic stream (use with --sharded "
                         "<w> to pick the workload; writes the "
                         "BENCH_TABLE.md section)")
    ap.add_argument("--wire", action="store_true",
                    help="process-sharded wire plane sweep: aggregate "
                         "wire deliveries/s over real sockets at "
                         "0/1/2 wire workers (hub + SO_REUSEPORT "
                         "worker pool over unix PeerLinks); writes "
                         "the BENCH_TABLE.md section")
    ap.add_argument("--wire-workers", default=None,
                    help="comma-separated pool sizes for --wire "
                         "(default 0,1,2)")
    ap.add_argument("--shm", action="store_true",
                    help="shared-memory match plane microbench: "
                         "in-process ring round-trip latency, "
                         "cross-lane fusion and churn-ack throughput "
                         "(`make shm-bench`); writes the "
                         "BENCH_TABLE.md section")
    ap.add_argument("--wire-one", default=None, type=int,
                    help="single wire-plane measurement at this pool "
                         "size (the sweep's inner subprocess)")
    ap.add_argument("--wire-shm", default=1, type=int,
                    help="--wire-one engine layout: 1 = shared-memory "
                         "match plane (default), 0 = per-process "
                         "engines (the pre-shm baseline)")
    ap.add_argument("--wire-resident", default=WIRE_RESIDENT, type=int,
                    help="resident filters seeded for the --wire-one "
                         "RSS measurement (after the throughput reps)")
    ap.add_argument("--wire-drain", default="auto",
                    choices=("auto", "native", "thread", "poll"),
                    help="--wire-one hub drain discipline (shm.drain) "
                         "— the sweep runs shm rows at poll AND auto "
                         "for the doorbell A/B")
    ap.add_argument("--shm-one", default=None,
                    choices=("auto", "poll"),
                    help="single shm-microbench arm at this drain "
                         "discipline (the --shm A/B's inner "
                         "subprocess; fresh interpreter per arm so "
                         "neither pays the other's engine generation)")
    ap.add_argument("--shm-fuse-us", default=0, type=int,
                    help="--shm-one adaptive fusion window "
                         "(shm.fuse_window_us) in µs")
    ap.add_argument("--churn-capacity", action="store_true",
                    help="single churn-capacity measurement at the "
                         "current ETPU_POOL_THREADS (the sweep's inner "
                         "subprocess)")
    ns = ap.parse_args()
    if ns.churn_capacity:
        stats = run_churn_capacity(ns.subs or 1_000_000)
        print(json.dumps(stats))
        return
    if ns.churn:
        rows = run_churn_sweep(subs=ns.subs)
        best = max(rows, key=lambda r: r.get("plane_rps") or 0)
        base = rows[0]
        print(json.dumps({
            "metric": "churn_apply_ops_per_sec",
            "value": round(best.get("plane_rps") or 0.0, 1),
            "unit": "ops/sec",
            "vs_baseline": round(
                (best.get("plane_rps") or 0.0)
                / max(base.get("python_rps") or 1.0, 1.0), 2),
            "workers": best["workers"],
            "n_resident": best["n_resident"],
            "rows": rows,
            "host_threads": os.cpu_count() or 1,
        }))
        return
    if ns.wire_one is not None:
        stats = asyncio.run(_wire_run_one(
            ns.wire_one, duration=4.0, reps=3, n_subs=30, n_pubs=2,
            payload=128, shm=bool(ns.wire_shm),
            resident=ns.wire_resident, drain=ns.wire_drain,
        ))
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps(stats))
        return
    if ns.shm_one is not None:
        stats = run_shm(drain=ns.shm_one, fuse_window_us=ns.shm_fuse_us)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps(stats))
        return
    if ns.shm:
        stats = run_shm_ab()
        _update_shm_table(stats)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        best = stats["arms"][-1] if stats["arms"] else {}
        print(json.dumps({
            "metric": "shm_tick_p50_us",
            "value": best.get("tick_p50_us"),
            "unit": "us",
            **{k: v for k, v in stats.items()},
        }))
        return
    if ns.wire:
        sizes = tuple(
            int(x) for x in (ns.wire_workers or "0,1,2").split(",")
        )
        stats = run_wire(sizes)
        _update_wire_table(stats)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        rows = stats["rows"]
        by_case = {(r["workers"], bool(r.get("shm"))): r for r in rows}
        best = max(rows, key=lambda r: r["rps"])
        w1_off = by_case.get((1, False))
        w1_on = by_case.get((1, True))
        w2_on = by_case.get((2, True))
        # no-regression gate: shared-engine w1 vs the per-process path
        w1_shared_vs_perproc = (
            round(w1_on["rps"] / w1_off["rps"], 2)
            if (w1_on and w1_off and w1_off["rps"]) else None
        )
        # memory gate: per-worker RSS flat from W=1 to W=2 (shm rows)
        rss_growth_pct = None
        if w1_on and w2_on:
            r1 = list((w1_on.get("worker_rss_mb") or {}).values())
            r2 = list((w2_on.get("worker_rss_mb") or {}).values())
            if r1 and r2 and r1[0]:
                m2 = sorted(r2)[len(r2) // 2]
                rss_growth_pct = round((m2 / r1[0] - 1.0) * 100.0, 1)
        print(json.dumps({
            "metric": "wire_deliveries_per_sec_sharded",
            "value": round(best["rps"], 1),
            "unit": "deliveries/sec",
            "workers": best["workers"],
            "vs_inproc": round(best.get("vs_inproc") or 1.0, 2),
            "w1_vs_inproc": round(
                (w1_on or {}).get("vs_inproc") or 0.0, 2),
            "w1_shared_vs_perproc": w1_shared_vs_perproc,
            "grp_max_w2": (w2_on or {}).get("grp_max", 0),
            "grp_gt1_pct_w2": (w2_on or {}).get("grp_gt1_pct", 0.0),
            "worker_rss_growth_w1_to_w2_pct": rss_growth_pct,
            "host_threads": stats["host_threads"],
            "rows": [
                {k: v for k, v in r.items() if k != "conns"}
                for r in rows
            ],
        }))
        return
    if ns.spans_shm_one is not None:
        stats = asyncio.run(_spans_shm_one(bool(ns.spans_shm_one),
                                           drain=ns.spans_drain))
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({k: v for k, v in stats.items()
                          if k != "fleet"}))
        return
    if ns.spans_shm:
        stats = run_spans_shm()
        _update_spans_shm_table(stats)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "shm_leg_recon_deviation_pct",
            "value": stats.get("recon_pct"),
            "unit": "pct_vs_measured_roundtrip",
            "gate_pct": stats["recon_gate_pct"],
            "overhead_pct": stats["overhead_pct"],
            "overhead_gate_pct": stats["overhead_gate_pct"],
            "rps_armed": stats["rps_armed"],
            "rps_disarmed": stats["rps_disarmed"],
            "legs": stats["legs"],
            "ring": stats["ring"],
            "leg_mean_sum_ms": stats["leg_mean_sum_ms"],
            "drain_cycle_ms": stats.get("drain_cycle_ms"),
            "group_sizes": stats.get("group_sizes"),
            "drain_mode": stats.get("drain_mode"),
            "poll": {k: v for k, v in (stats.get("poll") or {}).items()
                     if k != "legs"},
        }))
        return
    if ns.spans:
        stats = run_spans()
        if "stages" in stats:
            _update_spans_table(stats)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "span_disarmed_overhead_pct_fanout_wire",
            "value": round(stats.get("overhead_pct", 0.0), 5),
            "unit": "pct_of_per_delivery_cost",
            "gate_pct": SPAN_OVERHEAD_GATE_PCT,
            "worst_case_pct": round(
                stats.get("overhead_worst_case_pct", 0.0), 3),
            "boundary_check_ns": stats.get("boundary_check_ns", 0.0),
            "per_delivery_ns": stats.get("per_delivery_ns", 0.0),
            "armed_overhead_pct": round(
                stats.get("armed_overhead_pct") or 0.0, 2),
            "wire_rps_disarmed": round(stats["wire_rps_disarmed"], 1),
            "wire_rps_armed": round(stats.get("wire_rps_armed", 0.0), 1),
            "stage_p50_ms": stats.get("stage_p50_ms", {}),
            "stage_p99_ms": stats.get("stage_p99_ms", {}),
            "forward_legs_closed": stats.get("forward_legs_closed", 0),
        }))
        return
    if ns.fanout:
        stats = run_fanout()
        _update_fanout_table(stats)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "fanout_wire_deliveries_per_sec_50k",
            "value": round(stats["wire_rps_50k"], 1),
            "unit": "deliveries/sec",
            "vs_baseline": round(stats["vs_pre_rework_50k"], 2),
            "flat_ratio_10k_100k": round(
                stats["flat_ratio_10k_100k"], 2),
            "flat_ratio_1k_100k": round(stats["flat_ratio_1k_100k"], 2),
            "prefix_cache": stats["prefix_cache"],
            "rows": [
                {k: (round(v, 1) if isinstance(v, float) else v)
                 for k, v in r.items()}
                for r in stats["rows"]
            ],
        }))
        return
    if ns.takeover:
        stats = run_takeover()
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "takeover_bytes_reduction",
            "value": round(stats["bytes_reduction"], 1),
            "unit": "x_fewer_bytes_vs_materialized",
            "latency_speedup": round(stats["latency_speedup"], 2),
            "materialized_bytes": stats["materialized"]["wire_bytes"],
            "handoff_bytes": stats["handoff"]["wire_bytes"],
            "materialized_ms": round(
                stats["materialized"]["takeover_ms"], 1),
            "handoff_ms": round(stats["handoff"]["takeover_ms"], 1),
            "n_msgs": stats["n_msgs"],
        }))
        return
    if ns.ds:
        stats = run_ds()
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "ds_offline_fanout_resume_speedup",
            "value": round(stats["resume_speedup"], 2),
            "unit": "x_vs_legacy_snapshots",
            "park_tick_speedup": round(stats["park_tick_speedup"], 2),
            "legacy_resume_ms": round(
                stats["legacy"]["resume_total_ms"], 1),
            "ds_resume_ms": round(stats["ds"]["resume_total_ms"], 1),
            "n_sessions": stats["n_sessions"],
            "n_msgs": stats["n_msgs"],
        }))
        return
    if ns.restore:
        stats = run_restore(ns.subs or 100_000)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "engine_restore_speedup_100k",
            "value": round(stats["speedup"], 2),
            "unit": "x_vs_cold_rebuild",
            "restore_ms": round(stats["restore_ms"], 1),
            "rebuild_ms": round(stats["rebuild_ms"], 1),
            "bulk_rebuild_ms": round(stats["bulk_ms"], 1),
            "vs_bulk_rebuild": round(stats["speedup_vs_bulk"], 2),
            "n_filters": stats["n_filters"],
        }))
        return
    if ns.retained:
        stats = run_retained_sweep()
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        s0 = stats["populations"][0]
        print(json.dumps({
            "metric": "retained_lookups_per_sec_100k",
            "value": round(s0["dev_rps"], 1),
            "unit": "lookups/sec",
            "vs_baseline": round(s0["dev_rps"] / s0["host_rps"], 2),
            "kernel_rps": round(s0["kernel_rps"]),
            "batch_rows": s0["batch_rows"],
        }))
        return
    if ns.semantic:
        stats = run_semantic()
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        s0 = stats["populations"][0]
        print(json.dumps({
            "metric": "semantic_matches_per_sec_256q",
            "value": round(s0["dev_rps"], 1),
            "unit": "matches/sec",
            "vs_host_dense": round(s0["dev_rps"] / s0["host_rps"], 2),
            "kernel_rps": round(s0["kernel_rps"]),
            "e2e_pub_rps": round(stats["e2e"]["pub_rps"], 1),
            "batch_rows": s0["batch_rows"],
        }))
        return
    if ns.prep_only:
        stats = run_prep_only(ns.sharded if ns.sharded is not None else 2)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(json.dumps({
            "metric": "fused_prep_speedup_b512",
            "value": round(stats["speedups"].get(512, 0.0), 2),
            "unit": "x_vs_python_fallback",
            "speedup_b2048": round(stats["speedups"].get(2048, 0.0), 2),
            "rows": [
                {k: (round(v, 2) if isinstance(v, float) else v)
                 for k, v in r.items()}
                for r in stats["rows"]
            ],
        }))
        return
    if ns.config is None and ns.sharded is None:
        ns.all = True  # driver contract: plain `python bench.py` = full table

    if ns.sharded is not None:
        stats = run_sharded(ns.subs, workload=ns.sharded)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        _update_mesh_table(stats)
        ph = stats.get("phases", {})
        print(json.dumps({
            "metric": f"sharded_route_lookups_per_sec_{CONFIGS[ns.sharded][0]}",
            "value": round(stats["tpu_rps"]),
            "unit": "lookups/sec",
            "vs_baseline": round(stats["tpu_rps"] / stats["cpu_rps"], 2),
            "device": stats["device"],
            "n_devices": stats["n_devices"],
            "p99_ms": round(stats["p99_ms"], 3),
            "rps_depth1": round(stats["rps_depth1"]),
            "pipeline_depth": stats["pipeline_depth"],
            "pipeline_ratio": round(stats["pipeline_ratio"], 2),
            "occ_mean": round(stats["occ_mean"], 1),
            "prep_occ_mean": round(stats["prep_occ_mean"], 1),
            "group_mean": round(stats["group_mean"], 1),
            "prep_ms": round(ph.get("prep_ms", 0.0), 3),
            "dispatch_ms": round(ph.get("dispatch_ms", 0.0), 3),
            "prep_degraded": stats["prep_degraded"],
            "memo_hits": stats["memo_hits"],
            "memo_misses": stats["memo_misses"],
        }))
        return

    if not ns.all:
        init_device()  # probe the accelerator BEFORE the population build
        stats = run_config(ns.config, ns.subs)
        if ns.emit_stats:
            with open(ns.emit_stats, "w", encoding="utf-8") as f:
                json.dump(stats, f)
        print(headline_json(ns.config, stats))
        return

    # One fresh interpreter per config: measured empirically, running the
    # configs sequentially in one process degrades the steady-state match
    # latency of every config after the first by ~1000x (per-call device
    # overhead appears once a second table generation exists) — isolating
    # each run keeps every number a clean single-table measurement.
    import subprocess
    import sys
    import tempfile

    rows = {}
    for n in sorted(CONFIGS):
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
            stats_path = tf.name
        cmd = [sys.executable, os.path.abspath(__file__),
               "--config", str(n), "--emit-stats", stats_path]
        if ns.subs is not None:
            cmd += ["--subs", str(ns.subs)]
        r = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=3600)
        if r.returncode != 0:
            raise SystemExit(f"config {n} failed (rc={r.returncode})")
        with open(stats_path, "r", encoding="utf-8") as f:
            rows[n] = json.load(f)
        os.unlink(stats_path)
    # sharded engine rows (own interpreters: virtual CPU mesh)
    sharded_rows = {}
    for w in (2, 3, 5):
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
            stats_path = tf.name
        cmd = [sys.executable, os.path.abspath(__file__),
               "--sharded", str(w), "--emit-stats", stats_path]
        if ns.subs is not None:
            cmd += ["--subs", str(ns.subs)]
        r = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=3600)
        if r.returncode == 0:
            with open(stats_path, "r", encoding="utf-8") as f:
                sharded_rows[w] = json.load(f)
        else:
            log(f"sharded bench w{w} failed (rc={r.returncode}); row omitted")
        os.unlink(stats_path)
    sharded = sharded_rows.get(2)
    # retained-index row (own interpreter: fresh device state)
    retained = None
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        stats_path = tf.name
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--retained",
         "--emit-stats", stats_path],
        stdout=subprocess.PIPE, timeout=3600,
    )
    if r.returncode == 0:
        with open(stats_path, "r", encoding="utf-8") as f:
            retained = json.load(f)
    else:
        log(f"retained bench failed (rc={r.returncode}); row omitted")
    os.unlink(stats_path)
    with open("BENCH_TABLE.md", "w", encoding="utf-8") as f:
        f.write("# BASELINE.json workload table\n\n")
        f.write("hybrid = the PRODUCTION match path (`engine.match()` with "
                "broker.hybrid arbitration, exact verification ON): the "
                "engine serves each tick from whichever of the fused "
                "native host probe / device dispatch is measured faster, "
                "with probes keeping the HBM mirror warm.  device e2e = "
                "the same call forced through the device dispatch, "
                "pipelined three deep.  kernel = `match_batch_jit` on "
                "pre-hashed, pre-uploaded batches (the device data-plane "
                "roofline).  p99 = unpipelined single-batch latency at "
                f"{BATCH}.  Config 5's churn rides the fused delta+match "
                "dispatch on the device path and synchronous host-array "
                "updates on the host path.\n\n")
        up = rows[2].get("link_up_mbs", 0)
        down = rows[2].get("link_down_mbs", 0)
        f.write(
            "**Why arbitration**: this rig reaches the TPU over a tunnel "
            f"measured at ~{up:.0f} MB/s up / ~{down:.1f} MB/s down with "
            "~100 ms/op latency and multi-second stalls; at the e2e wire "
            "format the downlink alone caps device e2e below the CPU "
            "baseline, so round-3 shipped 0.3-0.6x e2e.  The reference "
            "never pays a wire to match (`emqx_router.erl:127-140`); the "
            "hybrid engine restores that guarantee by serving from the "
            "same table arrays host-side (identical semantics, native "
            "fused probe+verify) whenever the measured device round-trip "
            "is slower, and switches back when the link recovers.  The "
            "kernel columns remain the transfer-free device rate — on "
            "co-located hardware the arbiter picks the device path.\n\n"
            "**Device-e2e wire floor**: a device-matched topic ships 2 "
            "hash lanes x 4 B x L levels (L=8 after depth truncation: "
            "64 B/topic up) plus the sparse fid return (~4 B/hit "
            f"down); at the measured ~{up:.0f} MB/s uplink that caps "
            f"UNIQUE-topic traffic near ~{up * 1e6 / 64:,.0f} "
            "lookups/s before any compute — which is where the "
            "device-e2e column lands for configs 2/3 (unique names).  "
            "Submit-time dedup divides those bytes by the duplication "
            "factor, which is why the Zipf/production-shaped configs "
            "(1, 4) now WIN e2e over the same wire.\n\n")
        f.write("| # | config | filters | cpu lookups/s | hybrid lookups/s "
                "| hybrid speedup | hybrid p99 ms (4096 / 512) | "
                "device e2e | device e2e speedup | kernel lookups/s | "
                "kernel speedup | kernel p99 ms | insert/s | "
                "insert speedup |\n")
        f.write("|---|--------|---------|---------------|---------------|"
                "-------------|------------|------------|------------|"
                "------------------|----------------|---------------|"
                "----------|----------|\n")
        for n, s in rows.items():
            # match-speedup columns baseline against the CLEAN cpu rate:
            # config 5's under-load rate collapses toward zero (demand >
            # single-core capacity), which is the right denominator for
            # the under-load north-star row but noise for a match-rate
            # comparison
            clean = s.get("cpu_rps_clean", s["cpu_rps"])
            f.write(
                f"| {n} | {CONFIGS[n][1]} | {s['n_filters']:,} "
                f"| {clean:,.0f} | {s['tpu_rps']:,.0f} "
                f"| {s['tpu_rps']/clean:.1f}x "
                f"| {s['p99_ms']:.2f} / {s.get('p99_small_ms', 0):.2f} "
                f"| {s['dev_e2e_rps']:,.0f} "
                f"| {s['dev_e2e_rps']/clean:.1f}x "
                f"| {s['kernel_rps']:,.0f} "
                f"| {s['kernel_rps']/clean:.1f}x "
                f"| {s['kernel_p99_ms']:.2f} "
                f"| {s['insert_rps']:,.0f} "
                f"| {s['insert_rps']/s['cpu_insert_rps']:.1f}x |\n")

        # ---------------------------------------------- north-star table
        s2 = rows[2]
        f.write(
            "\n## North-star operating points (BASELINE.md: >=10x AND "
            "p99 < 2 ms at ONE tick size)\n\n"
            "Sustained throughput and per-tick p99 measured at the SAME "
            "tick size on the production hybrid path (verify on; config "
            "5 pays its 5%/sec churn inside the measured loop, paced by "
            "wall clock — and the CPU baseline pays the identical churn "
            "rate on its trie, per the workload's \"incremental rebuild "
            "under load\"; its speedup column divides by that "
            "UNDER-LOAD cpu rate, and a row only PASSes if it also "
            "sustained >=90% of the churn target).  Config 5's floor "
            "on this host is churn-apply capacity: 5%/sec of 10M "
            "routes = 500k subscribe/unsubscribe ops/s against ONE "
            "core — the engine's measured apply capacity is the churn/s "
            "column (the cpu trie saturates likewise), so both sides "
            "shed load and no tick "
            "size meets the p99 gate while drowning; passing needs "
            "more cores for the route bookkeeping or a lower absolute "
            "churn rate (`python bench.py --config 5 --subs 500000` "
            "reproduces the same 5%/s fraction at a demand within "
            "single-core capacity, where the gates pass — see "
            "COVERAGE.md round-5 notes).  Cores: baseline = "
            f"{s2.get('baseline_threads', 1)} thread; engine host probe "
            f"= {s2.get('match_threads', 1)} of "
            f"{s2.get('host_threads', 1)} hardware thread(s) on this "
            "host — with one core there is no parallel-host upper bound "
            "beyond the single-thread rate shown, so the speedup column "
            "is also the engine-vs-parallel-CPU-host ratio.\n\n"
            "| # | best tick | lookups/s | speedup | p99 ms | churn/s | "
            ">=10x | <2ms | gates |\n"
            "|---|---|---|---|---|---|---|---|---|\n"
        )
        for n, s in rows.items():
            best, _passed = pick_north_star(s.get("ns_rows"), s["cpu_rps"],
                                s.get("churn_target", 0.0))
            if best is None:
                continue
            ok10 = best["rps"] >= 10 * s["cpu_rps"]
            ok2 = best["p99_ms"] < 2.0
            churn_col = (
                f"{best['churn_rps']:,.0f}" if "churn_rps" in best else "—"
            )
            f.write(
                f"| {n} | {best['tick']} | {best['rps']:,.0f} "
                f"| {best['rps']/s['cpu_rps']:.1f}x "
                f"| {best['p99_ms']:.2f} | {churn_col} "
                f"| {'yes' if ok10 else 'NO'} | {'yes' if ok2 else 'NO'} "
                f"| {'PASS' if _passed else 'fail'} |\n")
        f.write(
            "\nFull sweep (per config: tick -> lookups/s @ p99 ms): "
        )
        for n, s in rows.items():
            nsr = s.get("ns_rows") or []
            f.write(f"\n- config {n}: " + ", ".join(
                f"{r['tick']}→{r['rps']:,.0f}@{r['p99_ms']:.2f}"
                for r in nsr))
        f.write("\n")
        if sharded_rows:
            single = {
                k: rows[2][k]
                for k in ("n_filters", "tpu_rps", "cpu_rps", "p99_ms",
                          "insert_rps")
            }
            # stash for later single-workload marker updates
            with open(_stash_path("BENCH_mesh_single.json"), "w",
                      encoding="utf-8") as sf:
                json.dump(single, sf)
            for w, s in sharded_rows.items():
                with open(_stash_path(f"BENCH_mesh_w{w}.json"), "w",
                          encoding="utf-8") as sf:
                    json.dump(s, sf)
            f.write("\n".join(
                _mesh_section_lines(sharded_rows, single)
            ) + "\n")
        if retained is not None:
            f.write(
                "\n## Retained-index lookup (subscribe-time wildcard "
                "fan-in)\n\n"
                "Mixed filter set (one-'+' pairs, '#' prefixes, exact "
                "names); device = the BUCKETED `models/retained.py` "
                "index (per-shape masked-hash keys, batched packed "
                "probes, exact verification ON, parity asserted per "
                "filter vs the trie); host = the retainer trie walk "
                "(`emqx_retainer_mnesia.erl` analog).  Lookups batch "
                "through the retainer (channel.py SUBSCRIBE packets, "
                "iter_matching), so device lookups/s is swept over the "
                "batch size B; kernel = the probe dispatch alone on "
                "resident arrays (no staging upload / result download). "
                " arbiter picks = index/trie serve counts from driving "
                "the rate-measured retainer arbitration on this rig.\n\n"
                "| stored names | host trie lookups/s | B | device "
                "index lookups/s | device vs host | kernel lookups/s | "
                "arbiter picks |\n"
                "|---|---|---|---|---|---|---|\n"
            )
            for s in retained.get("populations", [retained]):
                arb = s.get("arb", {})
                for i, br in enumerate(s.get("batch_rows", [])):
                    head = (f"{s['n_names']:,}", f"{s['host_rps']:,.1f}",
                            f"{s['kernel_rps']:,.0f}",
                            f"index={s['arb_index']} "
                            f"trie={s['arb_trie']} "
                            f"final={arb.get('final')}") if i == 0 \
                        else ("", "", "", "")
                    f.write(
                        f"| {head[0]} | {head[1]} | {br['batch']} "
                        f"| {br['dev_rps']:,.1f} "
                        f"| {br['dev_rps']/s['host_rps']:.2f}x "
                        f"| {head[2]} | {head[3]} |\n"
                    )
        # host dispatch fan-out (match excluded): flat per-delivery cost
        log("running delivery-plane fan-out bench")
        fstats = run_fanout(reps=3)
        f.write("\n".join(_fanout_section_lines(fstats)))
    log("wrote BENCH_TABLE.md")
    print(headline_json(2, rows[2]))


if __name__ == "__main__":
    main()
