"""Benchmark: TPU topic-match engine vs CPU trie baseline.

Reproduces the reference's in-tree microbench methodology
(`apps/emqx/src/emqx_broker_bench.erl`: N subscribers insert filters, M
publishers measure LookupRps) on BASELINE.md config #2: 100k subscriptions,
6-level topics, 20% single-level '+' wildcards.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = TPU route-lookups/sec over the CPU dict-trie baseline
(the reference's ETS-trie analog) measured in the same process.
"""

from __future__ import annotations

import json
import random
import sys
import time

import numpy as np

N_SUBS = 100_000
BATCH = 4096
N_BATCHES = 8
ITERS = 40
CPU_LOOKUPS = 3000


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_population(rng: random.Random):
    """100k filters over 6-level topic space, 20% '+' wildcards."""
    filters = []
    for i in range(N_SUBS):
        ws = [
            "device",
            str(rng.randint(0, 999)),
            rng.choice(["temp", "hum", "acc", "gps"]),
            str(rng.randint(0, 99)),
            rng.choice(["raw", "agg"]),
            str(i % 4096),
        ]
        r = rng.random()
        if r < 0.20:  # single-level wildcard somewhere
            ws[rng.randint(1, 5)] = "+"
        elif r < 0.25:  # a few multi-level
            cut = rng.randint(2, 5)
            ws = ws[:cut] + ["#"]
        filters.append("/".join(ws))
    return filters


def make_topics(rng: random.Random, n: int):
    return [
        [
            "device",
            str(rng.randint(0, 999)),
            rng.choice(["temp", "hum", "acc", "gps"]),
            str(rng.randint(0, 99)),
            rng.choice(["raw", "agg"]),
            str(rng.randint(0, 4095)),
        ]
        for _ in range(n)
    ]


def main() -> None:
    rng = random.Random(1234)
    t0 = time.time()
    filters = build_population(rng)

    # ---- CPU baseline: dict trie (ETS-trie analog) ----
    from emqx_tpu.models.reference import CpuTrieIndex

    trie = CpuTrieIndex()
    ins0 = time.time()
    for i, f in enumerate(filters):
        trie.insert(f, i)
    cpu_insert_rps = N_SUBS / (time.time() - ins0)

    cpu_topics = ["/".join(w) for w in make_topics(rng, CPU_LOOKUPS)]
    m0 = time.time()
    hits = 0
    for t in cpu_topics:
        hits += len(trie.match(t))
    cpu_rps = CPU_LOOKUPS / (time.time() - m0)
    log(
        f"cpu baseline: insert {cpu_insert_rps:,.0f}/s, "
        f"lookup {cpu_rps:,.0f}/s ({hits} hits), build {time.time()-t0:.1f}s"
    )

    # ---- TPU engine ----
    import jax

    from emqx_tpu.broker import topic as topiclib
    from emqx_tpu.models.engine import TopicMatchEngine
    from emqx_tpu.ops import hashing
    from emqx_tpu.ops.match import TopicBatch, match_batch_jit

    try:
        dev = jax.devices()[0]
    except RuntimeError as e:
        log(f"TPU backend unavailable ({e}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
    log(f"device: {dev.platform} {dev}")

    eng = TopicMatchEngine()
    ins0 = time.time()
    for f in filters:
        eng.add_filter(f)
    log(f"engine insert: {N_SUBS/(time.time()-ins0):,.0f}/s")
    tables = eng.sync_device()

    # pre-hash topic batches (host hashing measured separately; the data
    # plane rate is the device matcher)
    batches = []
    hash_secs = 0.0
    for _ in range(N_BATCHES):
        ts = ["/".join(w) for w in make_topics(rng, BATCH)]
        h0 = time.time()
        # C++ fast path (split+fnv+mix in one pass) when built, else Python
        ta, tb, ln, dl = hashing.hash_topics(eng.space, ts)
        hash_secs += time.time() - h0
        batches.append(
            TopicBatch(*(jax.device_put(x, dev) for x in (ta, tb, ln, dl)))
        )
    host_hash_rps = N_BATCHES * BATCH / hash_secs

    c0 = time.time()
    out = match_batch_jit(tables, batches[0])
    out.block_until_ready()
    log(f"first compile+run: {time.time()-c0:.1f}s")

    r0 = time.time()
    for i in range(ITERS):
        out = match_batch_jit(tables, batches[i % N_BATCHES])
    out.block_until_ready()
    elapsed = time.time() - r0
    tpu_rps = ITERS * BATCH / elapsed

    matched = np.asarray(out)
    log(
        f"tpu: {tpu_rps:,.0f} lookups/s ({elapsed*1e3/ITERS:.2f} ms/batch of "
        f"{BATCH}); host hash {host_hash_rps:,.0f}/s; "
        f"sample hits {(matched >= 0).sum()}"
    )

    print(
        json.dumps(
            {
                "metric": "route_lookups_per_sec_100k_subs",
                "value": round(tpu_rps),
                "unit": "lookups/sec",
                "vs_baseline": round(tpu_rps / cpu_rps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
